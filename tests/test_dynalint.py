"""dynalint (dynamo_tpu/analysis): rule fixtures + the repo-wide CI gate.

Layout:
- one positive AND one negative fixture per AST rule (R1-R25), the
  positives for R1/R2 being faithful minimal copies of the PRE-FIX
  ADVICE r5 bugs (spec.py salt-id drafts, _decode_kernel_prefix missing
  stale-tail zeroing) — the analyzer must flag both on the pre-fix
  shapes and stay quiet on the fixed ones;
- one positive and one negative per jaxpr invariant (J1-J5);
- the gate: the analyzer over dynamo_tpu/ plus the engine entry-point
  audit yields zero non-baseline findings, so this tier-1 pytest run IS
  the CI gate for new findings.
"""
import json
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.analysis import (
    audit_bucket_ladder, audit_donation, filter_baseline, lint_source,
    load_baseline, run_lint, save_baseline, trace_and_audit,
)
from dynamo_tpu.analysis.findings import Finding

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "tools", "dynalint_baseline.json")


def lint(src):
    return lint_source(textwrap.dedent(src), "fixture.py")


def rules(findings):
    return {f.rule for f in findings}


# -- R1: unguarded vocab gathers ----------------------------------------------

# faithful minimal copy of the PRE-FIX ngram_propose shape (ADVICE r5
# high): token ids sliced from raw history, returned with no vocab bound
PREFIX_NGRAM = """
    import numpy as np

    def ngram_propose(tokens, k, min_ngram=2, max_ngram=4):
        arr = np.asarray(tokens, dtype=np.int64)
        cont = arr[len(arr) - k:]
        return [int(x) for x in cont]
"""


def test_r1_flags_prefix_ngram_propose():
    assert "R1" in rules(lint(PREFIX_NGRAM))


def test_r1_quiet_on_fixed_ngram_propose():
    fixed = """
        import numpy as np

        def ngram_propose(tokens, k, min_ngram=2, max_ngram=4,
                          vocab_size=None):
            arr = np.asarray(tokens, dtype=np.int64)
            cont = [int(x) for x in arr[len(arr) - k:]]
            if vocab_size is not None:
                for i, x in enumerate(cont):
                    if not 0 <= x < vocab_size:
                        return cont[:i]
            return cont
    """
    assert "R1" not in rules(lint(fixed))


def test_r1_flags_unclamped_embedding_take():
    pos = """
        import jax.numpy as jnp

        def embed(params, ids):
            return jnp.take(params["embed"], ids, axis=0)
    """
    assert "R1" in rules(lint(pos))


def test_r1_quiet_on_clamped_take_and_axis_subscripts():
    neg = """
        import jax.numpy as jnp

        def embed(params, ids, vocab):
            x = jnp.take(params["embed"], jnp.clip(ids, 0, vocab - 1),
                         axis=0)
            return x[:, None] + params["embed"][..., None].sum()
    """
    assert "R1" not in rules(lint(neg))


def test_r1_live_on_current_spec_py():
    """The satellite fix must keep spec.py / engine.py R1-clean."""
    for rel in ("dynamo_tpu/engine/spec.py", "dynamo_tpu/engine/engine.py"):
        with open(os.path.join(REPO, rel)) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R1"], rel


# -- R2: Pallas kernels missing stale-tail K/V zeroing ------------------------

# faithful minimal copy of the PRE-FIX _decode_kernel_prefix per-head
# loop (ADVICE r5 medium): packed kernel contracting unmasked K and V
PREFIX_KERNEL = """
    import jax
    import jax.numpy as jnp

    def _decode_kernel_prefix(ps, hkv, g, hd, pack, q_shifts, k_buf,
                              v_buf, slot, prefix):
        outs = []
        for j in range(hkv):
            k = k_buf[slot, j].astype(jnp.float32)
            v = v_buf[slot, j].astype(jnp.float32)
            sc = jax.lax.dot_general(
                q_shifts[j], k, (((1,), (1,)), ((), ())))
            p = jnp.exp(sc)
            outs.append(jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ()))))
        return outs
"""


def test_r2_flags_prefix_kernel_without_masking():
    found = [f for f in lint(PREFIX_KERNEL) if f.rule == "R2"]
    assert len(found) == 2  # both the K and the V contraction


def test_r2_quiet_when_vpos_masked():
    fixed = """
        import jax
        import jax.numpy as jnp

        def _decode_kernel_prefix(ps, hkv, g, hd, pack, q_shifts, k_buf,
                                  v_buf, slot, prefix, tail_ok):
            outs = []
            for j in range(hkv):
                k = k_buf[slot, j].astype(jnp.float32)
                v = v_buf[slot, j].astype(jnp.float32)
                k = jnp.where(tail_ok, k, 0.0)
                v = jnp.where(tail_ok, v, 0.0)
                sc = jax.lax.dot_general(
                    q_shifts[j], k, (((1,), (1,)), ((), ())))
                p = jnp.exp(sc)
                outs.append(jax.lax.dot_general(
                    p, v, (((1,), (0,)), ((), ()))))
            return outs
    """
    assert "R2" not in rules(lint(fixed))


def test_r2_unpacked_kernel_k_is_exempt():
    """Non-packed kernels (no `pack` arg) mask K's scores with NEG_INF
    instead — lanes never mix tokens, so only V needs zeroing."""
    unpacked = """
        import jax
        import jax.numpy as jnp

        def _decode_kernel(ps, g, q, k_buf, v_buf, slot, kv_len, vrow):
            k = k_buf[slot].astype(jnp.float32)
            v = v_buf[slot].astype(jnp.float32)
            v = jnp.where(vrow < kv_len, v, 0.0)
            sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())))
            p = jnp.exp(sc)
            return jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())))
    """
    assert "R2" not in rules(lint(unpacked))


def test_r2_live_on_current_paged_attention():
    with open(os.path.join(REPO, "dynamo_tpu/ops/paged_attention.py")) as f:
        found = lint_source(f.read(), "dynamo_tpu/ops/paged_attention.py")
    assert not [x for x in found if x.rule == "R2"]


# -- R3: blocking calls in async defs -----------------------------------------

def test_r3_flags_blocking_sleep_in_async():
    pos = """
        import time

        async def handler():
            time.sleep(1.0)
    """
    assert "R3" in rules(lint(pos))


def test_r3_quiet_on_asyncio_sleep_and_sync_fns():
    neg = """
        import asyncio
        import time

        async def handler():
            await asyncio.sleep(1.0)

        def sync_loop():
            time.sleep(1.0)

        async def outer():
            def helper():
                time.sleep(0.1)  # runs in an executor, not the loop
            return helper
    """
    assert "R3" not in rules(lint(neg))


def test_r3_inline_disable():
    src = """
        import time

        async def handler():
            time.sleep(1.0)  # dynalint: disable=R3
    """
    assert "R3" not in rules(lint(src))


# -- R4: CancelledError-swallowing handlers -----------------------------------

def test_r4_flags_bare_and_base_exception():
    pos = """
        def f(work):
            try:
                work()
            except:
                pass

        def g(work):
            try:
                work()
            except BaseException:
                return None
    """
    assert len([f for f in lint(pos) if f.rule == "R4"]) == 2


def test_r4_quiet_on_reraise_and_exception():
    neg = """
        def f(work, cleanup):
            try:
                work()
            except BaseException:
                cleanup()
                raise

        def g(work):
            try:
                work()
            except Exception:
                pass  # CancelledError derives from BaseException: safe
    """
    assert "R4" not in rules(lint(neg))


# -- R5: mutating a container while iterating it ------------------------------

def test_r5_flags_mutation_while_iterating():
    pos = """
        def prune(d):
            for k in d:
                if k < 0:
                    d.pop(k)

        def prune_del(d):
            for k in d.keys():
                del d[k]
    """
    assert len([f for f in lint(pos) if f.rule == "R5"]) == 2


def test_r5_quiet_on_snapshot_iteration():
    neg = """
        def prune(d):
            for k in list(d):
                if k < 0:
                    d.pop(k)

        def other(d, e):
            for k in d:
                e.pop(k, None)
    """
    assert "R5" not in rules(lint(neg))


# -- R6: host syncs in hot-path files -----------------------------------------

HOT_SRC = """
    # dynalint: hot-path
    import jax

    def step(x):
        return float(x.sum()) + x.max().item()
"""


def test_r6_flags_host_sync_in_hot_path_file():
    assert len([f for f in lint(HOT_SRC) if f.rule == "R6"]) == 2


def test_r6_quiet_without_marker():
    assert "R6" not in rules(lint(HOT_SRC.replace("hot-path", "")))


# -- R7: unbounded transport awaits in serving layers -------------------------

R7_SRC = """
    import asyncio

    async def dispatch(messaging, subject, payload):
        return await messaging.request(subject, payload)

    async def consume(queue):
        return await queue.dequeue_leased()

    async def dial(host, port):
        return await asyncio.open_connection(host, port)
"""


def test_r7_flags_unbounded_transport_awaits_in_scope():
    found = lint_source(textwrap.dedent(R7_SRC),
                        "dynamo_tpu/frontend/fixture.py")
    assert len([f for f in found if f.rule == "R7"]) == 3


def test_r7_quiet_outside_serving_layers():
    # same awaits in engine/device code: exempt (bounded by computation,
    # not by a remote peer)
    found = lint_source(textwrap.dedent(R7_SRC),
                        "dynamo_tpu/engine/fixture.py")
    assert "R7" not in rules(found)


def test_r7_quiet_on_bounded_awaits():
    neg = """
        import asyncio
        from dynamo_tpu.runtime.deadline import with_deadline

        async def dispatch(messaging, subject, payload, ctx):
            return await with_deadline(
                messaging.request(subject, payload, timeout=30.0),
                30.0, ctx)

        async def consume(queue):
            return await queue.dequeue_leased(timeout=1.0, lease_s=30.0)

        async def dial(host, port):
            return await asyncio.wait_for(
                asyncio.open_connection(host, port), 10.0)

        async def fire_and_forget(messaging, subject, payload):
            await messaging.publish(subject, payload)  # not a round trip
    """
    found = lint_source(textwrap.dedent(neg),
                        "dynamo_tpu/disagg/fixture.py")
    assert "R7" not in rules(found)


def test_r7_live_on_current_serving_layers():
    """The reliability PR must keep the serving layers R7-clean (every
    control-plane round trip bounded)."""
    import glob
    scoped = []
    for pat in ("dynamo_tpu/runtime/transports/*.py",
                "dynamo_tpu/frontend/*.py", "dynamo_tpu/disagg/*.py"):
        scoped.extend(glob.glob(os.path.join(REPO, pat)))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R7"], rel


# -- R8: blocking device syncs inside hot-path regions ------------------------

R8_SRC = """
    import jax
    import numpy as np

    def commit(outs, dev_aux):
        # dynalint: hot-path-begin
        toks = jax.device_get(outs)
        dev_aux.block_until_ready()
        host = np.asarray(dev_aux)
        # dynalint: hot-path-end
        return toks, host
"""


def test_r8_flags_syncs_in_region():
    assert len([f for f in lint(R8_SRC) if f.rule == "R8"]) == 3


def test_r8_quiet_outside_region():
    # same code with no region markers: R8 does not apply (R6 needs the
    # file-level marker, which this fixture also lacks)
    stripped = R8_SRC.replace("hot-path-begin", "").replace(
        "hot-path-end", "")
    assert "R8" not in rules(lint(stripped))


def test_r8_quiet_on_annotated_sync_point():
    neg = """
        import jax
        import numpy as np

        def commit(outs, other):
            # dynalint: hot-path-begin
            toks = jax.device_get(outs)  # dynalint: sync-point — the one
            #   intended per-window output fetch
            host = np.asarray(toks)   # toks came from device_get: host view
            counts = np.zeros((4,), np.int32)
            counts2 = np.asarray(counts)  # numpy-born: free view, no sync
            # dynalint: hot-path-end
            return host, counts2
    """
    assert "R8" not in rules(lint(neg))


def test_r8_region_does_not_trip_file_level_r6():
    # hot-path-begin/end scope a REGION for R8; they must not opt the
    # whole file into R6 (which would flag host code outside the region)
    src = """
        import jax

        def region(outs):
            # dynalint: hot-path-begin
            x = outs
            # dynalint: hot-path-end
            return x

        def boundary(outs):
            return jax.device_get(outs)
    """
    assert "R6" not in rules(lint(src))


def test_r8_live_on_engine_decode_region():
    """The pipelined decode staging/dispatch region in engine/engine.py
    must stay R8-clean: every blocking sync there carries an explicit
    `# dynalint: sync-point` justification."""
    path = os.path.join(REPO, "dynamo_tpu", "engine", "engine.py")
    with open(path) as f:
        src = f.read()
    assert "# dynalint: hot-path-begin" in src   # the region exists
    found = lint_source(src, "dynamo_tpu/engine/engine.py")
    assert not [f for f in found if f.rule == "R8"]


# -- R9: swallowed exceptions in the serving layers ---------------------------

R9_SRC = """
    import logging

    log = logging.getLogger("x")

    async def notify(messaging, subject, payload):
        try:
            await messaging.publish(subject, payload)
        except Exception:
            log.exception("notify failed")

    def parse(payload):
        try:
            return int(payload)
        except Exception:
            pass
"""


def test_r9_flags_pass_and_log_and_continue_in_scope():
    found = lint_source(textwrap.dedent(R9_SRC),
                        "dynamo_tpu/runtime/fixture.py")
    assert len([f for f in found if f.rule == "R9"]) == 2


def test_r9_quiet_outside_serving_layers():
    # engine code is out of scope: exceptions there surface through the
    # step loop, not past a peer-recovery mechanism
    found = lint_source(textwrap.dedent(R9_SRC),
                        "dynamo_tpu/engine/fixture.py")
    assert "R9" not in rules(found)


def test_r9_quiet_on_annotation_handling_and_narrow_types():
    neg = """
        import logging

        log = logging.getLogger("x")

        async def notify(messaging, subject, payload):
            try:
                await messaging.publish(subject, payload)
            except Exception:  # dynalint: swallow-ok=receiver-timeout-covers-it
                log.exception("notify failed")

        def parse(payload, fallback):
            try:
                return int(payload)
            except Exception:
                return fallback          # real handling: a fallback value

        def narrow(payload):
            try:
                return int(payload)
            except (ValueError, TypeError):
                pass                     # deliberate narrow types: quiet
    """
    found = lint_source(textwrap.dedent(neg),
                        "dynamo_tpu/disagg/fixture.py")
    assert "R9" not in rules(found)


def test_r9_live_on_current_serving_layers():
    """Every swallowed exception in runtime/, disagg/, frontend/ carries
    a `# dynalint: swallow-ok=<reason>` annotation (the satellite audit
    annotated all 20 pre-existing sites)."""
    import glob
    scoped = []
    for pat in ("dynamo_tpu/runtime/**/*.py", "dynamo_tpu/frontend/*.py",
                "dynamo_tpu/disagg/*.py"):
        scoped.extend(glob.glob(os.path.join(REPO, pat), recursive=True))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R9"], rel


# -- R10: unbucketed leading dims in schedule()-reachable plan builders -------

R10_SRC = """
    import numpy as np

    def _build_mixed(batch, tb):
        tokens = np.zeros((len(batch), tb), np.int32)
        return tokens
"""


def test_r10_flags_unbucketed_leading_dim_in_plan_builder():
    found = lint_source(textwrap.dedent(R10_SRC),
                        "dynamo_tpu/engine/scheduler_fixture.py")
    assert "R10" in rules(found)


def test_r10_quiet_outside_planning_scope_and_functions():
    # same shape outside the engine planning layer: not schedule()-
    # reachable, out of scope
    found = lint_source(textwrap.dedent(R10_SRC),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R10" not in rules(found)
    # helper not matching the planner naming (not schedule()-reachable
    # plan construction): quiet even in scope
    helper = """
        import numpy as np

        def pack_payload(items):
            return np.zeros((len(items),), np.int32)
    """
    found = lint_source(textwrap.dedent(helper),
                        "dynamo_tpu/engine/scheduler_fixture.py")
    assert "R10" not in rules(found)


def test_r10_quiet_on_bucketed_dims_and_annotation():
    neg = """
        import numpy as np

        def _build_prefill(batch, tb, buckets):
            bb = next_bucket(len(batch), buckets)
            tokens = np.zeros((bb, tb), np.int32)
            # dynalint: bucketed — row count is config-fixed max_slots
            extra = np.full((len(batch), 1), -1, np.int32)
            return tokens, extra
    """
    found = lint_source(textwrap.dedent(neg),
                        "dynamo_tpu/engine/scheduler_fixture.py")
    assert "R10" not in rules(found)


def test_r10_live_on_current_planning_layer():
    """The mixed-step planner (and everything else schedule()-reachable)
    builds only bucketed per-step arrays."""
    for rel in ("dynamo_tpu/engine/scheduler.py",
                "dynamo_tpu/engine/engine.py"):
        with open(os.path.join(REPO, rel)) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R10"], rel


# -- R11: raw KV-cache leaf access outside the quant codec helpers ------------

R11_SRC = """
    import jax.numpy as jnp

    def leaky_read(cache, page_table):
        k = cache["k"].astype(jnp.float32)     # bytes-as-values
        return jnp.take(k, page_table, axis=1)
"""


def test_r11_flags_raw_cache_leaf_access_in_model_code():
    found = lint_source(textwrap.dedent(R11_SRC),
                        "dynamo_tpu/models/fixture.py")
    assert "R11" in rules(found)


def test_r11_quiet_outside_scope_and_in_codec_module():
    # frontend code never touches cache leaves' numerics: out of scope
    found = lint_source(textwrap.dedent(R11_SRC),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R11" not in rules(found)
    # the codec module itself is exempt — it IS the decode/encode site
    found = lint_source(textwrap.dedent(R11_SRC),
                        "dynamo_tpu/ops/kv_quant.py")
    assert "R11" not in rules(found)


def test_r11_quiet_on_annotated_codec_sites():
    neg = """
        import jax.numpy as jnp
        from dynamo_tpu.ops.kv_quant import dequantize_rows

        def codec_read(cache, page_table):
            # dynalint: kv-codec — codec read site
            g = jnp.take(cache["k"], page_table, axis=1)
            # dynalint: kv-codec — scale rows feed the dequant
            s = jnp.take(cache["k_scale"], page_table, axis=1)
            return dequantize_rows(g, s, jnp.bfloat16)
    """
    found = lint_source(textwrap.dedent(neg),
                        "dynamo_tpu/models/fixture.py")
    assert "R11" not in rules(found)


def test_r11_live_on_current_model_and_ops_tree():
    """Every cache-leaf access in the model/ops/engine-step code is
    codec-annotated (the kv_quant PR's boundary stays mechanically
    enforced)."""
    for rel in ("dynamo_tpu/models/llama.py", "dynamo_tpu/models/pp.py",
                "dynamo_tpu/engine/engine.py",
                "dynamo_tpu/ops/attention.py",
                "dynamo_tpu/ops/paged_attention.py"):
        with open(os.path.join(REPO, rel)) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R11"], rel


# -- R12: control-plane retry loops without backoff+jitter --------------------

R12_SRC = """
    import asyncio

    async def watch_loop(kv, prefix, apply):
        while True:
            try:
                snapshot, events = await kv.watch_prefix(prefix)
                async for ev in events:
                    apply(ev)
            except Exception:
                await asyncio.sleep(0.1)   # hot, synchronized retry
"""


def test_r12_flags_retry_loop_without_backoff():
    found = lint_source(textwrap.dedent(R12_SRC),
                        "dynamo_tpu/runtime/watch_fixture.py")
    assert "R12" in rules(found)


def test_r12_quiet_outside_scope_and_without_retry_shape():
    # engine code is out of scope (no control-plane reconnects there)
    found = lint_source(textwrap.dedent(R12_SRC),
                        "dynamo_tpu/engine/fixture.py")
    assert "R12" not in rules(found)
    # a loop that does NOT survive failures (no handler) is not a retry
    # loop — death is handled a layer up
    no_handler = """
        async def watch_once(kv, prefix, apply):
            while True:
                snapshot, events = await kv.watch_prefix(prefix)
                async for ev in events:
                    apply(ev)
    """
    found = lint_source(textwrap.dedent(no_handler),
                        "dynamo_tpu/runtime/watch_fixture.py")
    assert "R12" not in rules(found)


def test_r12_quiet_with_backoff_or_annotation():
    with_backoff = """
        from dynamo_tpu.runtime.backoff import Backoff

        async def watch_loop(kv, prefix, apply):
            backoff = Backoff()
            while True:
                try:
                    snapshot, events = await kv.watch_prefix(prefix)
                    async for ev in events:
                        apply(ev)
                    backoff.reset()
                except Exception:
                    await backoff.sleep()
    """
    found = lint_source(textwrap.dedent(with_backoff),
                        "dynamo_tpu/runtime/watch_fixture.py")
    assert "R12" not in rules(found)
    annotated = """
        import asyncio

        async def heartbeat(lease, ttl):
            # dynalint: backoff-ok=TTL-paced renewal cadence
            while True:
                try:
                    lease.keep_alive()
                except Exception:
                    pass
                await asyncio.sleep(ttl / 3)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/runtime/hb_fixture.py")
    assert "R12" not in rules(found)


def test_r12_live_on_current_control_plane_tree():
    """Every surviving control-plane retry loop in runtime/, frontend/,
    kv_router/ either drives its delay through runtime/backoff.py or
    carries a justified fixed-cadence annotation."""
    import glob
    scoped = []
    for pat in ("dynamo_tpu/runtime/**/*.py", "dynamo_tpu/frontend/*.py",
                "dynamo_tpu/kv_router/*.py"):
        scoped.extend(glob.glob(os.path.join(REPO, pat), recursive=True))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R12"], rel


# -- R13: span lifecycle + hot-path span deferral ------------------------------

def test_r13_flags_begin_span_without_guaranteed_end():
    leaky = """
        from dynamo_tpu.runtime.tracing import TRACER

        async def serve_one(trace, req):
            span = TRACER.begin_span("serve", trace)
            if req.bad:
                return None          # span leaks on this path
            result = await req.run()
            TRACER.end_span(span)
            return result
    """
    assert "R13" in rules(lint(leaky))


def test_r13_quiet_on_with_form_and_try_finally():
    with_form = """
        from dynamo_tpu.runtime.tracing import TRACER

        async def serve_one(trace, req):
            with TRACER.span("serve", trace) as sp:
                sp.set(n=1)
                return await req.run()
    """
    assert "R13" not in rules(lint(with_form))
    finally_form = """
        from dynamo_tpu.runtime.tracing import TRACER

        async def serve_one(trace, req):
            span = TRACER.begin_span("serve", trace)
            try:
                return await req.run()
            finally:
                TRACER.end_span(span)
    """
    assert "R13" not in rules(lint(finally_form))
    annotated = """
        from dynamo_tpu.runtime.tracing import TRACER

        async def serve_one(trace, req, finish_cb):
            # dynalint: span-ok=ends-in-the-idempotent-finish-callback
            span = TRACER.begin_span("serve", trace)
            finish_cb.register(span)
            return await req.run()
    """
    assert "R13" not in rules(lint(annotated))


def test_r13_flags_span_recording_in_hot_path_region():
    hot = """
        from dynamo_tpu.runtime.tracing import TRACER

        def _pipeline_step(self, plan, trace):
            # dynalint: hot-path-begin
            with TRACER.span("window", trace):
                outs = self._dispatch_staged(plan)
            TRACER.event("emit", trace, n=len(outs))
            # dynalint: hot-path-end
            return outs
    """
    found = [x for x in lint(hot) if x.rule == "R13"]
    assert len(found) == 2          # the span AND the event


def test_r13_quiet_on_deferred_recorder_in_region():
    deferred = """
        from dynamo_tpu.runtime.tracing import TRACER

        def _pipeline_step(self, plan, t0, dt):
            # dynalint: hot-path-begin
            outs = self._dispatch_staged(plan)
            TRACER.defer_phase("engine", "dispatch", dt)
            # dynalint: hot-path-end
            return outs
    """
    assert "R13" not in rules(lint(deferred))
    # outside a region the same recording calls are fine
    cold = """
        from dynamo_tpu.runtime.tracing import TRACER

        def commit(self, plan, trace):
            TRACER.event("emit", trace, n=1)
    """
    assert "R13" not in rules(lint(cold))


def test_r13_live_on_current_tree():
    """Every begin_span in the live tree ends on all paths (or carries a
    justified span-ok), and no hot-path region records spans directly —
    the engine's regions route through PhaseTimer -> defer_phase."""
    import glob
    scoped = sorted(glob.glob(os.path.join(REPO, "dynamo_tpu/**/*.py"),
                              recursive=True))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R13"], rel


# -- R14: unbounded raw stream IO on the wire ----------------------------------

R14_SRC = """
    import asyncio
    from dynamo_tpu.runtime.transports.wire import read_frame, write_frame

    async def retire_ack(reader, writer, frame):
        write_frame(writer, frame)
        await writer.drain()               # unbounded flush
        return await read_frame(reader)    # unbounded ack read
"""


def test_r14_flags_unbounded_stream_io_in_scope():
    found = lint_source(textwrap.dedent(R14_SRC),
                        "dynamo_tpu/disagg/xfer_fixture.py")
    assert len([x for x in found if x.rule == "R14"]) == 2  # drain + read
    found = lint_source(textwrap.dedent(R14_SRC),
                        "dynamo_tpu/runtime/transports/tcp_fixture.py")
    assert "R14" in rules(found)


def test_r14_quiet_outside_scope():
    # the frontend's awaits are R7's territory; raw-IO scope is the
    # disagg data plane and the transport implementations
    found = lint_source(textwrap.dedent(R14_SRC),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R14" not in rules(found)


def test_r14_quiet_on_bounded_and_annotated_io():
    bounded = """
        import asyncio
        from dynamo_tpu.runtime.transports.wire import read_frame, write_frame

        async def retire_ack(self, reader, writer, frame, deadline):
            write_frame(writer, frame)
            await asyncio.wait_for(writer.drain(), self._io_timeout(deadline))
            return await read_frame(reader, timeout=self._io_timeout(deadline))
    """
    found = lint_source(textwrap.dedent(bounded),
                        "dynamo_tpu/disagg/xfer_fixture.py")
    assert "R14" not in rules(found)
    annotated = """
        from dynamo_tpu.runtime.transports.wire import read_frame

        async def pump(self, reader):
            while True:
                # dynalint: unbounded-io-ok=idle-client-connections-are-
                # legal; peer death surfaces as EOF
                frame = await read_frame(reader)
                self.dispatch(frame)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/runtime/transports/srv_fixture.py")
    assert "R14" not in rules(found)


def test_r14_live_on_data_and_control_wire():
    """Every raw stream read/write in disagg/ and runtime/transports/
    is bounded (timeout kwarg, wait_for) or carries a justified
    unbounded-io-ok annotation — the tentpole's per-IO timeout
    discipline, held by machine."""
    import glob
    scoped = []
    for pat in ("dynamo_tpu/disagg/*.py",
                "dynamo_tpu/runtime/transports/*.py"):
        scoped.extend(glob.glob(os.path.join(REPO, pat)))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R14"], rel


# -- R15: metric registration contract ----------------------------------------

R15_BAD = """
    from dynamo_tpu.observability.metrics import MetricsRegistry
    r = MetricsRegistry()
    undocumented = r.gauge("llm_mystery_gauge_nobody_wrote_down",
                           "has help but no catalog entry")
    helpless = r.gauge("llm_workers", "")
    missing_help = r.counter("llm_workers")
"""


def test_r15_flags_undocumented_family_and_empty_help():
    found = lint_source(textwrap.dedent(R15_BAD),
                        "dynamo_tpu/observability/fixture.py")
    r15 = [x for x in found if x.rule == "R15"]
    assert len(r15) == 3
    msgs = " ".join(x.message for x in r15)
    assert "not in the" in msgs and "no help text" in msgs


def test_r15_quiet_on_documented_families_and_fstring_fragments():
    good = """
        def build(r, name):
            # exact literal: catalog member
            g = r.gauge("llm_workers", "Live worker instances")
            # f-string fragments resolve against the catalog
            # (llm_cp_* families)
            cp = {n: r.gauge(f"llm_cp_{n}", f"control plane: {n}")
                  for n in ("watch_resyncs",)}
            # histogram with keyword help
            h = r.histogram("llm_ttft_seconds",
                            help_="time to first token")
            # dynalint: metric-doc-ok=fixture-internal scratch gauge
            s = r.gauge("llm_scratch_not_documented", "x")
            return g, cp, h, s
    """
    found = lint_source(textwrap.dedent(good),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R15" not in rules(found)


def test_r15_quiet_outside_package_scope():
    found = lint_source(textwrap.dedent(R15_BAD).replace(
        "dynamo_tpu.observability.metrics", "metrics"),
        "tools/fixture.py")
    assert "R15" not in rules(found)


def test_r15_live_every_registration_documented_with_help():
    """The live gate: every metric registration in the dynamo_tpu
    package carries help text and a docs/OBSERVABILITY.md §9 catalog
    entry (the static half; test_metrics_catalog.py holds the
    rendered half)."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R15"], \
            (rel, [x.message for x in found if x.rule == "R15"])


# -- R16: transfer-cost fallback contract --------------------------------------

R16_BAD = """
    def pick_worker(model, workers, nbytes):
        # ranks purely on the scalar estimate: a never-sampled link's
        # prior is indistinguishable from a measurement here
        return min(workers, key=lambda w: model.estimate_s(w, nbytes))
"""


def test_r16_flags_unhandled_scalar_estimate():
    found = lint_source(textwrap.dedent(R16_BAD),
                        "dynamo_tpu/kv_router/fixture.py")
    assert "R16" in rules(found)
    found = lint_source(textwrap.dedent(R16_BAD), "tools/fixture.py")
    assert "R16" in rules(found)


def test_r16_quiet_outside_scope():
    found = lint_source(textwrap.dedent(R16_BAD), "examples/fixture.py")
    assert "R16" not in rules(found)
    # generic `.estimate` on a non-cost receiver is not a target
    other = """
        def eta(tracker, job):
            return tracker.estimate(job)
    """
    found = lint_source(textwrap.dedent(other),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R16" not in rules(found)


def test_r16_quiet_on_handled_and_annotated_consumers():
    handled = """
        def pick_worker(model, workers, nbytes):
            best, best_cost = None, float("inf")
            for w in workers:
                est = model.estimate(w, nbytes)
                cost = est.seconds * (2.0 if est.cold else 1.0)
                if cost < best_cost:
                    best, best_cost = w, cost
            return best

        def drain_time(model, link):
            if not model.measured(link):
                return None
            return model.estimate_s(link, model.backlog_bytes(link))
    """
    found = lint_source(textwrap.dedent(handled),
                        "dynamo_tpu/kv_router/fixture.py")
    assert "R16" not in rules(found)
    annotated = """
        def rough_eta(model, link, nbytes):
            # dynalint: cost-fallback-ok=display-only ETA, the prior is
            # exactly what we want to show for unmeasured links
            return model.estimate_s(link, nbytes)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/observability/fixture.py")
    assert "R16" not in rules(found)


def test_r16_live_on_cost_model_consumers():
    """Every live consumer of the cost model's queries (the selector,
    the send path, the model's own delegating methods) handles the
    cold/frozen/default branch or carries a justified annotation."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R16"], \
            (rel, [x.message for x in found if x.rule == "R16"])


# -- R17: actuation pacing contract --------------------------------------------

R17_BAD = """
    async def rebalance_loop(workers):
        while True:
            for w in workers:
                await w.mark_draining()
"""


def test_r17_flags_unpaced_actuation_loop():
    found = lint_source(textwrap.dedent(R17_BAD),
                        "dynamo_tpu/runtime/fixture.py")
    assert "R17" in rules(found)
    found = lint_source(textwrap.dedent(R17_BAD), "tools/fixture.py")
    assert "R17" in rules(found)


def test_r17_flags_controller_tick_without_pacing():
    tick = """
        async def tick(self, served_endpoint, role):
            await served_endpoint.re_role(role)
    """
    found = lint_source(textwrap.dedent(tick),
                        "dynamo_tpu/runtime/fixture.py")
    assert "R17" in rules(found)


def test_r17_quiet_outside_scope_and_on_non_actuators():
    found = lint_source(textwrap.dedent(R17_BAD), "examples/fixture.py")
    assert "R17" not in rules(found)
    # `.drain()` on a non-worker receiver (stream writers, ledgers,
    # tracers) is not an actuation
    other = """
        async def pump(writer, ledger):
            while True:
                await writer.drain()
                ledger.drain(clear=True)
    """
    found = lint_source(textwrap.dedent(other),
                        "dynamo_tpu/runtime/fixture.py")
    assert "R17" not in rules(found)
    # a one-shot actuation outside any loop/tick is an operator action
    oneshot = """
        async def maintenance(served_endpoint):
            await served_endpoint.drain(timeout_s=30.0)
    """
    found = lint_source(textwrap.dedent(oneshot),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R17" not in rules(found)


def test_r17_quiet_on_paced_and_annotated_actuators():
    paced = """
        async def actuate(self, decisions, workers):
            # the controller's cooldown+hysteresis pace these drains
            if not self.cooldown.ready(self.now()):
                return
            for d in decisions:
                await workers[d.worker].set_role(d.to_role)
    """
    found = lint_source(textwrap.dedent(paced),
                        "dynamo_tpu/runtime/fixture.py")
    assert "R17" not in rules(found)
    annotated = """
        async def storm(workers):
            for w in workers:
                # dynalint: actuation-ok=seeded chaos storm driver, not
                # a controller; the whole point is unpaced churn
                await w.mark_draining()
    """
    found = lint_source(textwrap.dedent(annotated),
                        "tools/fixture.py")
    assert "R17" not in rules(found)


def test_r17_live_on_actuation_call_sites():
    """Every live drain/re-role call site in a loop or controller tick
    engages pacing (the autoscaler's Cooldown/Hysteresis, a Backoff, a
    seeded jitter) or carries a justified annotation."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R17"], \
            (rel, [x.message for x in found if x.rule == "R17"])


# -- R18: shared-pool verification contract ------------------------------------

R18_BAD = """
    def warm(pool, seq_hash, mode):
        # moves pool bytes with no word on where the capture sum is
        # checked — the shape R18 exists to catch
        return pool.fetch(seq_hash, mode)
"""


def test_r18_flags_unreferenced_pool_fetch():
    found = lint_source(textwrap.dedent(R18_BAD),
                        "dynamo_tpu/engine/fixture.py")
    assert "R18" in rules(found)
    found = lint_source(textwrap.dedent(R18_BAD), "tools/fixture.py")
    assert "R18" in rules(found)
    publish = """
        def tee(kv_pool, sh, parent, th, arrays):
            kv_pool.publish("w0", sh, parent, th, arrays)
    """
    found = lint_source(textwrap.dedent(publish),
                        "dynamo_tpu/engine/fixture.py")
    assert "R18" in rules(found)


def test_r18_quiet_outside_scope_and_on_non_pool_receivers():
    found = lint_source(textwrap.dedent(R18_BAD), "examples/fixture.py")
    assert "R18" not in rules(found)
    # generic fetch/publish on non-pool receivers is not a target
    other = """
        async def push(component, subject, payload):
            await component.publish(subject, payload)

        def load(store, key):
            return store.fetch(key)
    """
    found = lint_source(textwrap.dedent(other),
                        "dynamo_tpu/runtime/fixture.py")
    assert "R18" not in rules(found)


def test_r18_quiet_on_referenced_and_annotated_pool_paths():
    handled = """
        def warm(pool, seq_hash, mode):
            # bytes are verified against the traveling capture checksum
            # inside fetch(); a mismatch quarantines and returns None
            return pool.fetch(seq_hash, mode)
    """
    found = lint_source(textwrap.dedent(handled),
                        "dynamo_tpu/engine/fixture.py")
    assert "R18" not in rules(found)
    annotated = """
        def poke(pool, seq_hash):
            # dynalint: pool-verify-ok=containment probe, no bytes move
            return pool.fetch(seq_hash, "")
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/engine/fixture.py")
    assert "R18" not in rules(found)


def test_r18_live_on_pool_call_sites():
    """Every live pool publish/fetch/claim/prefetch call site states
    where its checksum verification happens or carries a justified
    annotation (engine/kv_pool.py, scheduler._pool_claim, the engine
    publish tee, AdmissionPrefetcher)."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R18"], \
            (rel, [x.message for x in found if x.rule == "R18"])


# -- R19: starvation-bound contract --------------------------------------------

R19_BAD = """
    def make_room(scheduler, arrival):
        # preempts and class-orders with no visible bound: the high
        # class wins every contest here
        victim = select_victim(scheduler.running, below_prio=9)
        scheduler._preempt_one()
        return victim


    async def pump(queue):
        while True:
            item = await queue.dequeue_leased(timeout=1.0)
            if item:
                return item
"""


def test_r19_flags_unreferenced_preempt_and_dequeue():
    found = lint_source(textwrap.dedent(R19_BAD),
                        "dynamo_tpu/engine/fixture.py")
    r19 = [x for x in found if x.rule == "R19"]
    assert len(r19) == 3            # select_victim + _preempt_one + dequeue
    found = lint_source(textwrap.dedent(R19_BAD), "tools/fixture.py")
    assert "R19" in rules(found)


def test_r19_quiet_outside_scope_and_in_tests():
    found = lint_source(textwrap.dedent(R19_BAD), "examples/fixture.py")
    assert "R19" not in rules(found)
    found = lint_source(textwrap.dedent(R19_BAD),
                        "tests/fixture.py")
    assert "R19" not in rules(found)


def test_r19_quiet_on_referenced_and_annotated_sites():
    handled = """
        def make_room(scheduler, arrival):
            # victim starvation bounded by the class-band requeue +
            # queue aging limit (QosPolicy.aging_limit)
            victim = select_victim(scheduler.running, below_prio=9)
            scheduler._preempt_one()
            return victim
    """
    found = lint_source(textwrap.dedent(handled),
                        "dynamo_tpu/engine/fixture.py")
    assert "R19" not in rules(found)
    annotated = """
        async def pump(queue):
            while True:
                # dynalint: starvation-ok=single-class FIFO deployment
                item = await queue.dequeue_leased(timeout=1.0)
                if item:
                    return item
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/disagg/fixture.py")
    assert "R19" not in rules(found)


# -- R20: min-frontier aggregation contract ------------------------------------

R20_BAD = """
    def decide_fate(worker, rid, epoch):
        # trusts whatever one endpoint answers, silently
        pages = worker.server.committed_frontier(rid, epoch)
        if pages:
            worker.engine.salvage_remote(rid, pages)
        return pages


    def arm(engine, rid, first, needed, srv, epoch):
        engine.preactivate_remote(
            rid, first, needed,
            lambda: srv.stream_frontier(rid, epoch, 0))
"""


def test_r20_flags_unreferenced_frontier_consumers():
    found = lint_source(textwrap.dedent(R20_BAD),
                        "dynamo_tpu/disagg/fixture.py")
    r20 = [x for x in found if x.rule == "R20"]
    # committed_frontier + salvage_remote + preactivate_remote +
    # stream_frontier
    assert len(r20) == 4
    found = lint_source(textwrap.dedent(R20_BAD), "tools/fixture.py")
    assert "R20" in rules(found)


def test_r20_quiet_outside_scope_and_in_tests():
    found = lint_source(textwrap.dedent(R20_BAD), "examples/fixture.py")
    assert "R20" not in rules(found)
    found = lint_source(textwrap.dedent(R20_BAD), "tests/fixture.py")
    assert "R20" not in rules(found)


def test_r20_quiet_on_referenced_and_annotated_sites():
    handled = """
        def decide_fate(worker, rid, epoch):
            # frontier = MIN over per-stream frontiers (the
            # ShardedKvTransferGroup aggregation): salvage only keeps
            # pages every shard stream committed
            pages = worker.server.committed_frontier(rid, epoch)
            if pages:
                worker.engine.salvage_remote(rid, pages)
            return pages
    """
    found = lint_source(textwrap.dedent(handled),
                        "dynamo_tpu/disagg/fixture.py")
    assert "R20" not in rules(found)
    annotated = """
        def resume_point(srv, rid, epoch, sid):
            # dynalint: frontier-ok=per-stream resume handshake; fate
            # decisions still go through the min aggregation
            return srv.stream_frontier(rid, epoch, sid)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/disagg/fixture.py")
    assert "R20" not in rules(found)


# -- R22: placement-epoch contract ---------------------------------------------

R22_BAD = """
    def route_publish(ring, membership, key, payload):
        # caches placement with no word about when it expires
        targets = ring.owners_for(key)
        primary = ring.lookup(key)
        for hid in targets:
            payload.send(hid)
        return primary


    def price_pool(membership, score):
        if not membership.live_hosts():
            return 0
        return score
"""


def test_r22_flags_unreferenced_placement_consumers():
    found = lint_source(textwrap.dedent(R22_BAD),
                        "dynamo_tpu/engine/fixture.py")
    r22 = [x for x in found if x.rule == "R22"]
    # owners_for + ring.lookup + live_hosts
    assert len(r22) == 3
    found = lint_source(textwrap.dedent(R22_BAD), "tools/fixture.py")
    assert "R22" in rules(found)


def test_r22_quiet_outside_scope_tests_and_placement_layer():
    found = lint_source(textwrap.dedent(R22_BAD), "examples/fixture.py")
    assert "R22" not in rules(found)
    found = lint_source(textwrap.dedent(R22_BAD), "tests/fixture.py")
    assert "R22" not in rules(found)
    # the placement layer itself is exempt (it IS the epoch machinery,
    # the ops/kv_quant.py precedent from R11)
    found = lint_source(textwrap.dedent(R22_BAD),
                        "dynamo_tpu/runtime/placement.py")
    assert "R22" not in rules(found)


def test_r22_quiet_on_referenced_and_annotated_sites():
    handled = """
        def route_publish(ring, membership, key, payload):
            # owners re-resolved per call; every write carries the
            # membership epoch and serving hosts fence stale ones
            targets = ring.owners_for(key)
            for hid in targets:
                payload.send(hid)
    """
    found = lint_source(textwrap.dedent(handled),
                        "dynamo_tpu/engine/fixture.py")
    assert "R22" not in rules(found)
    annotated = """
        def snapshot_hosts(membership):
            # dynalint: ring-ok=read-only diagnosis snapshot, no
            # write or fetch is routed from this list
            return list(membership.live_hosts())
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/engine/fixture.py")
    assert "R22" not in rules(found)
    # bare `.lookup` on a non-ring receiver is not placement
    other = """
        def find(catalog, key):
            return catalog.lookup(key)
    """
    found = lint_source(textwrap.dedent(other),
                        "dynamo_tpu/engine/fixture.py")
    assert "R22" not in rules(found)


def test_r22_live_on_placement_call_sites():
    """Every live consumer of owners_for / ring.lookup / pool-host
    resolution speaks the ownership-epoch vocabulary or carries a
    justified annotation (pool_service fetch/publish/rebalance, the
    router's pool-host liveness fence)."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R22"], \
            (rel, [x.message for x in found if x.rule == "R22"])


# -- R23: one decode kernel ----------------------------------------------------

R23_BAD = """
    import functools
    import jax.experimental.pallas as pl


    def my_local_decode(q, k, v, ps, hkv):
        # a "quick local kernel" fork of the decode attention path
        return pl.pallas_call(
            functools.partial(_decode_kernel_fork, ps, hkv),
            grid=(4,),
        )(q, k, v)
"""


def test_r23_flags_decode_pallas_call_outside_dispatcher():
    found = lint_source(textwrap.dedent(R23_BAD),
                        "dynamo_tpu/engine/fixture.py")
    r23 = [x for x in found if x.rule == "R23"]
    assert len(r23) == 1
    found = lint_source(textwrap.dedent(R23_BAD), "tools/fixture.py")
    assert "R23" in rules(found)
    # a THIRD frozen copy pasted into the oracle module still flags
    found = lint_source(textwrap.dedent(R23_BAD),
                        "dynamo_tpu/ops/paged_attention_oracle.py")
    assert "R23" in rules(found)


def test_r23_quiet_outside_scope_and_in_dispatcher():
    found = lint_source(textwrap.dedent(R23_BAD), "examples/fixture.py")
    assert "R23" not in rules(found)
    # the unified dispatcher owns THE kernel — exempt (the
    # ops/kv_quant.py precedent from R11)
    found = lint_source(textwrap.dedent(R23_BAD),
                        "dynamo_tpu/ops/paged_attention.py")
    assert "R23" not in rules(found)
    # a pallas_call whose kernel is not decode attention stays quiet
    other = """
        import jax.experimental.pallas as pl


        def quantize(x):
            return pl.pallas_call(_quant_kernel, grid=(4,))(x)
    """
    found = lint_source(textwrap.dedent(other),
                        "dynamo_tpu/ops/fixture.py")
    assert "R23" not in rules(found)


def test_r23_quiet_on_annotated_sites():
    annotated = """
        import functools
        import jax.experimental.pallas as pl


        def frozen_oracle(q, ps, hkv):
            # dynalint: kernel-ok=frozen pre-PR-18 oracle fixture
            return pl.pallas_call(
                functools.partial(_decode_kernel_fork, ps, hkv),
                grid=(4,),
            )(q)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/engine/fixture.py")
    assert "R23" not in rules(found)


def test_r23_live_tree_has_one_decode_dispatcher():
    """The live tree dispatches decode attention through exactly one
    module: ops/paged_attention.py (exempt). The two frozen oracle
    call sites in ops/paged_attention_oracle.py carry
    `# dynalint: kernel-ok=` annotations; nothing else constructs a
    decode pallas_call."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R23"], \
            (rel, [x.message for x in found if x.rule == "R23"])


def test_r23_oracle_unreachable_from_engine():
    """Acceptance: the legacy kernels are demoted to test oracles —
    nothing under engine/ or models/ imports paged_attention_oracle."""
    import glob
    prod = glob.glob(os.path.join(REPO, "dynamo_tpu", "engine", "*.py"))
    prod += glob.glob(os.path.join(REPO, "dynamo_tpu", "models", "*.py"))
    assert prod
    for path in prod:
        with open(path) as f:
            src = f.read()
        assert "paged_attention_oracle" not in src, path


# -- R24: hedged-dispatch exactness --------------------------------------------

R24_BAD = """
    async def retry_faster(client, request):
        # "just fire a second copy if it's slow" — no race discipline,
        # no teardown, nothing stops a post-commit duplicate
        slot = client._start_hedge(request)
        return await slot
"""


def test_r24_flags_undisciplined_hedge_dispatch():
    found = lint_source(textwrap.dedent(R24_BAD),
                        "dynamo_tpu/frontend/fixture.py")
    r24 = [x for x in found if x.rule == "R24"]
    assert len(r24) == 1
    # a driver script forking hedges flags too — tools/ is in scope
    found = lint_source(textwrap.dedent(R24_BAD), "tools/fixture.py")
    assert "R24" in rules(found)


def test_r24_quiet_outside_scope():
    found = lint_source(textwrap.dedent(R24_BAD), "examples/fixture.py")
    assert "R24" not in rules(found)
    found = lint_source(textwrap.dedent(R24_BAD), "tests/fixture.py")
    assert "R24" not in rules(found)


def test_r24_quiet_when_function_speaks_the_discipline():
    disciplined = """
        async def hedge_race(client, request):
            # first frame wins; the loser is cancelled through the
            # abort path before any token is committed (pre-commit
            # only — a hedge never races a stream that has emitted)
            slot = client._start_hedge(request)
            return await slot
    """
    found = lint_source(textwrap.dedent(disciplined),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R24" not in rules(found)


def test_r24_quiet_on_annotated_sites():
    annotated = """
        async def replay_hedge(client, request):
            # dynalint: hedge-ok=offline replay of a recorded race
            slot = client._start_hedge(request)
            return await slot
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R24" not in rules(found)


def test_r24_live_tree_hedge_sites_disciplined():
    """The live tree dispatches hedges from exactly one place —
    frontend/reliability.py's first-token-wins race — and that call
    site speaks the first-wins / cancellation / pre-commit vocabulary,
    so the gate holds at zero findings."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R24"], \
            (rel, [x.message for x in found if x.rule == "R24"])


# -- R25: streamed window-pool claim/fill/victim discipline --------------------

R25_BAD = """
    def stage_segment(pool, key, views, lid):
        # "just stage the page" — nothing says why a stale half can't
        # be consumed or what guards the bytes coming off the tier
        pool.prefetch(key, views, lid)
        arrs, hit = pool.take(key, views, lid)
        return arrs
"""


def test_r25_flags_undisciplined_window_pool_sites():
    found = lint_source(textwrap.dedent(R25_BAD),
                        "dynamo_tpu/engine/fixture.py")
    r25 = [x for x in found if x.rule == "R25"]
    assert len(r25) == 2      # the fill AND the claim both flag
    # a driver script staging pages flags too — tools/ is in scope
    found = lint_source(textwrap.dedent(R25_BAD), "tools/fixture.py")
    assert "R25" in rules(found)
    # the victim leg flags on its own terminal
    victim = """
        def shrink(streamer, ss):
            streamer._spill_victims(ss)
    """
    found = lint_source(textwrap.dedent(victim),
                        "dynamo_tpu/engine/fixture.py")
    assert "R25" in rules(found)


def test_r25_quiet_outside_scope():
    found = lint_source(textwrap.dedent(R25_BAD), "examples/fixture.py")
    assert "R25" not in rules(found)
    found = lint_source(textwrap.dedent(R25_BAD), "tests/fixture.py")
    assert "R25" not in rules(found)


def test_r25_quiet_when_function_speaks_the_discipline():
    disciplined = """
        def stage_segment(pool, key, views, lid):
            # double buffer keyed by chained page hashes: a stale
            # prefetch never matches, and the cold views were already
            # checksum-verified at pin time (rot quarantines + only
            # the victim page recomputes)
            pool.prefetch(key, views, lid)
            arrs, hit = pool.take(key, views, lid)
            return arrs
    """
    found = lint_source(textwrap.dedent(disciplined),
                        "dynamo_tpu/engine/fixture.py")
    assert "R25" not in rules(found)
    # bare "stream"/"page" words must NOT satisfy the rule
    vague = """
        def stage_segment(pool, key, views, lid):
            # stream the page in
            pool.take(key, views, lid)
    """
    found = lint_source(textwrap.dedent(vague),
                        "dynamo_tpu/engine/fixture.py")
    assert "R25" in rules(found)


def test_r25_quiet_on_annotated_sites():
    annotated = """
        def warm_pool(pool, key, views, lid):
            # dynalint: stream-ok=offline warmup, no decode consumes this
            pool.prefetch(key, views, lid)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/engine/fixture.py")
    assert "R25" not in rules(found)


def test_r25_live_tree_window_pool_sites_disciplined():
    """The live tree touches the streamed window pool from exactly one
    module — engine/streaming.py's claim/fill/victim legs — and every
    enclosing function speaks the keyed-double-buffer / verify-on-fetch
    / checksummed-spill vocabulary, so the gate holds at zero."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R25"], \
            (rel, [x.message for x in found if x.rule == "R25"])


def test_r19_live_on_preemption_call_sites():
    """Every live preemption / victim-selection / class-ordered-dequeue
    call site references the aging/no-starvation bound or carries a
    justified annotation (engine/scheduler.py preempt paths, the
    disagg PrefillWorker consume loop, the QoS storm driver)."""
    import glob
    scoped = glob.glob(os.path.join(REPO, "dynamo_tpu", "**", "*.py"),
                       recursive=True)
    scoped += glob.glob(os.path.join(REPO, "tools", "*.py"))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R19"], \
            (rel, [x.message for x in found if x.rule == "R19"])


# -- layer 3: flow-sensitive escapes closed (flow.py) --------------------------
#
# One paired fixture per escape that docs/ANALYSIS.md used to list as a
# "Static limitation": the positive is a shape the PRE-flow lexical rule
# provably missed (the bug hides behind a name binding), the negative is
# the legitimate idiom the new recognition must keep quiet on.

def test_r7_flow_flags_timeout_variable_that_is_always_none():
    # pre-flow escape: `timeout=deadline` satisfied the lexical
    # "has a timeout kwarg" check even when the variable is None on
    # every reaching path — asyncio's wait-forever with extra steps
    leaky = """
        async def dispatch(messaging, subject, payload):
            deadline = None
            return await messaging.request(subject, payload,
                                           timeout=deadline)
    """
    found = lint_source(textwrap.dedent(leaky),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R7" in rules(found)


def test_r7_flow_quiet_when_variable_may_hold_a_budget():
    # a real constant budget through a binding: quiet
    bounded = """
        async def dispatch(messaging, subject, payload):
            t = 30.0
            return await messaging.request(subject, payload, timeout=t)
    """
    found = lint_source(textwrap.dedent(bounded),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R7" not in rules(found)
    # one path None, one path bounded: MAY hold a budget — benefit of
    # the doubt (the rule only fires on an all-paths-None proof)
    maybe = """
        async def dispatch(messaging, subject, payload, fast):
            t = None
            if fast:
                t = 5.0
            return await messaging.request(subject, payload, timeout=t)
    """
    found = lint_source(textwrap.dedent(maybe),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R7" not in rules(found)
    # parameter-fed timeout: incomplete constant set, no claim
    param = """
        async def dispatch(messaging, subject, payload, t=None):
            return await messaging.request(subject, payload, timeout=t)
    """
    found = lint_source(textwrap.dedent(param),
                        "dynamo_tpu/frontend/fixture.py")
    assert "R7" not in rules(found)


def test_r14_flow_flags_timeout_variable_that_is_always_none():
    leaky = """
        from dynamo_tpu.runtime.transports.wire import read_frame

        async def pump(reader):
            t = None
            return await read_frame(reader, timeout=t)
    """
    found = lint_source(textwrap.dedent(leaky),
                        "dynamo_tpu/runtime/transports/fixture.py")
    assert "R14" in rules(found)


def test_r14_flow_quiet_on_bound_timeout_variable():
    bounded = """
        from dynamo_tpu.runtime.transports.wire import read_frame

        async def pump(reader):
            t = 5.0
            return await read_frame(reader, timeout=t)
    """
    found = lint_source(textwrap.dedent(bounded),
                        "dynamo_tpu/runtime/transports/fixture.py")
    assert "R14" not in rules(found)


def test_r10_flow_follows_len_through_a_binding():
    # pre-flow escape: `n = len(batch)` one statement before the
    # allocation hid the data-dependent dim from the lexical
    # "len() inside the shape element" check
    leaky = """
        import numpy as np

        def _build_mixed(batch, tb):
            n = len(batch)
            tokens = np.zeros((n, tb), np.int32)
            return tokens
    """
    found = lint_source(textwrap.dedent(leaky),
                        "dynamo_tpu/engine/scheduler_fixture.py")
    assert "R10" in rules(found)


def test_r10_flow_quiet_when_len_is_laundered_through_a_bucket():
    # the binding derives from len() but passes through next_bucket():
    # admission-stable, exactly the idiom the planners use
    bucketed = """
        import numpy as np

        def _build_mixed(batch, tb, buckets):
            n = next_bucket(len(batch), buckets)
            tokens = np.zeros((n, tb), np.int32)
            return tokens
    """
    found = lint_source(textwrap.dedent(bucketed),
                        "dynamo_tpu/engine/scheduler_fixture.py")
    assert "R10" not in rules(found)


def test_r11_flow_tracks_cache_leaf_alias_into_float_math():
    # pre-flow escape: the annotated whole-page read was sanctioned,
    # but the ALIAS carried the quantized bytes into .astype(float)
    # three lines later where the lexical rule could not see them
    leaky = """
        import jax.numpy as jnp

        def leaky_alias(cache, page_table):
            # dynalint: kv-codec — whole-page move keeps representation
            k = cache["k"]
            moved = jnp.take(k, page_table, axis=2)
            cast = k.astype(jnp.float32)
            return moved, cast
    """
    found = lint_source(textwrap.dedent(leaky),
                        "dynamo_tpu/models/fixture.py")
    assert len([f for f in found if f.rule == "R11"]) == 1  # the astype
    # and through a cache-dict alias + arithmetic, same escape
    arith = """
        def mix(cache, scale):
            kv = cache
            k = kv["k"]
            return k * scale
    """
    found = lint_source(textwrap.dedent(arith),
                        "dynamo_tpu/models/fixture.py")
    assert "R11" in rules(found)


def test_r11_flow_quiet_on_representation_preserving_alias_use():
    # the alias only feeds whole-page moves / a dequantizing consumer:
    # no astype-to-float, no raw arithmetic — quiet
    neg = """
        import jax.numpy as jnp
        from dynamo_tpu.ops.kv_quant import dequantize_rows

        def codec_path(cache, page_table):
            # dynalint: kv-codec — whole-page move keeps representation
            k = cache["k"]
            g = jnp.take(k, page_table, axis=1)
            return dequantize_rows(g, None, jnp.bfloat16)
    """
    found = lint_source(textwrap.dedent(neg),
                        "dynamo_tpu/models/fixture.py")
    assert "R11" not in rules(found)
    # annotated downstream cast: the codec site moved, the annotation
    # moved with it
    annotated = """
        import jax.numpy as jnp

        def codec_cast(cache):
            # dynalint: kv-codec — capture for the dequant below
            k = cache["k"]
            # dynalint: kv-codec — dequant entry, scales applied inside
            return k.astype(jnp.float32)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/models/fixture.py")
    assert "R11" not in rules(found)


def test_r13_flow_flags_leak_despite_unrelated_try_finally():
    # pre-flow escape: the old heuristic blessed EVERY begin_span in a
    # function where SOME try/finally ended a span — this early return
    # leaks before the try is ever entered, and only the CFG sees it
    leaky = """
        from dynamo_tpu.runtime.tracing import TRACER

        async def serve_one(trace, req):
            span = TRACER.begin_span("serve", trace)
            if req.bad:
                return None          # leaks: the finally is never reached
            try:
                return await req.run()
            finally:
                TRACER.end_span(span)
    """
    assert "R13" in rules(lint(leaky))


def test_r13_flow_proves_branch_complete_and_loop_exit_endings():
    # branch-complete ending, no try/finally anywhere: the must-reach
    # proof is the only thing keeping this quiet
    branchy = """
        from dynamo_tpu.runtime.tracing import TRACER

        def run_one(trace, req):
            span = TRACER.begin_span("serve", trace)
            if req.fast:
                out = req.fast_path()
            else:
                out = req.slow_path()
            TRACER.end_span(span)
            return out
    """
    assert "R13" not in rules(lint(branchy))
    # continue inside try/finally: the back edge routes THROUGH the
    # finally, so every attempt's span still ends (the reliability
    # retry-machine shape)
    retry = """
        from dynamo_tpu.runtime.tracing import TRACER

        async def retry_loop(trace, req):
            while True:
                span = TRACER.begin_span("attempt", trace)
                try:
                    r = await req.run()
                    if r is None:
                        continue
                    return r
                finally:
                    TRACER.end_span(span)
    """
    assert "R13" not in rules(lint(retry))
    # span factory: the begin's result is returned — ownership (and the
    # end obligation) transfers to the caller
    factory = """
        from dynamo_tpu.runtime.tracing import TRACER

        def open_span(trace):
            return TRACER.begin_span("serve", trace)
    """
    assert "R13" not in rules(lint(factory))


# -- R21: await-interleaving TOCTOU (interleave.py) ----------------------------

R21_SRC = """
    async def route(self, rid, payload):
        worker = self.workers[rid]
        await self.queue.put(rid)
        return await worker.dispatch(payload)
"""


def test_r21_flags_stale_snapshot_committed_after_await():
    found = lint_source(textwrap.dedent(R21_SRC),
                        "dynamo_tpu/runtime/fixture.py")
    r21 = [f for f in found if f.rule == "R21"]
    assert len(r21) == 1
    assert "worker" in r21[0].message and "self.workers" in r21[0].message


def test_r21_quiet_outside_async_control_plane_scope():
    found = lint_source(textwrap.dedent(R21_SRC),
                        "dynamo_tpu/models/fixture.py")
    assert "R21" not in rules(found)


def test_r21_quiet_on_post_await_reread_and_fence():
    reread = """
        async def route(self, rid, payload):
            worker = self.workers[rid]
            await self.queue.put(rid)
            worker = self.workers.get(rid)   # use-time re-read
            if worker is None:
                raise KeyError(rid)
            return await worker.dispatch(payload)
    """
    found = lint_source(textwrap.dedent(reread),
                        "dynamo_tpu/runtime/fixture.py")
    assert "R21" not in rules(found)
    fenced = """
        async def commit_pages(self, rid, pages):
            seq = self.pending[rid]
            await self._stage(pages)
            if seq.epoch != self.lease_epoch(rid):   # fence check
                raise KeyError(rid)
            return seq.commit(pages)
    """
    found = lint_source(textwrap.dedent(fenced),
                        "dynamo_tpu/disagg/fixture.py")
    assert "R21" not in rules(found)


def test_r21_quiet_on_interleave_ok_annotation():
    annotated = """
        async def route(self, rid, payload):
            worker = self.workers[rid]
            await self.queue.put(rid)
            # dynalint: interleave-ok=dispatch-revalidates-liveness-and-
            # raises-on-a-deregistered-worker
            return await worker.dispatch(payload)
    """
    found = lint_source(textwrap.dedent(annotated),
                        "dynamo_tpu/runtime/fixture.py")
    assert "R21" not in rules(found)


def test_r21_live_on_async_control_plane():
    """The R21 sweep stays fully triaged: zero unannotated stale-snapshot
    commits across runtime/, disagg/, frontend/, kv_router/ (the one
    real race it found — LocalTransferBackend's pre-staging receiver
    snapshot — is FIXED, with a regression test in test_disagg.py)."""
    import glob
    scoped = []
    for pat in ("dynamo_tpu/runtime/**/*.py", "dynamo_tpu/disagg/*.py",
                "dynamo_tpu/frontend/*.py", "dynamo_tpu/kv_router/*.py"):
        scoped.extend(glob.glob(os.path.join(REPO, pat), recursive=True))
    assert scoped
    for path in scoped:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            found = lint_source(f.read(), rel)
        assert not [x for x in found if x.rule == "R21"], \
            (rel, [x.message for x in found if x.rule == "R21"])


# -- jaxpr invariants ----------------------------------------------------------

def test_j1_flags_float64_leak():
    with jax.experimental.enable_x64(True):
        found = trace_and_audit(
            "j1pos", lambda x: jnp.asarray(np.float64(2.0)) * x,
            jnp.zeros((4,), jnp.float32))
    assert "J1" in rules(found)


def test_j1_quiet_on_f32():
    found = trace_and_audit("j1neg", lambda x: x * 2.0,
                            jnp.zeros((4,), jnp.float32))
    assert not found


def test_j2_flags_unconsumable_donation():
    found = audit_donation(
        "j2pos", lambda a, b: a * 1.0, (1,),
        jnp.zeros((4,), jnp.float32), jnp.zeros((8,), jnp.float32))
    assert rules(found) == {"J2"}


def test_j2_quiet_when_output_matches():
    found = audit_donation(
        "j2neg", lambda a, b: (a.sum(), b + 1.0), (1,),
        jnp.zeros((4,), jnp.float32), jnp.zeros((8,), jnp.float32))
    assert not found


def test_j3_flags_dead_rung_and_escape():
    from dynamo_tpu.engine.scheduler import next_bucket
    dead = audit_bucket_ladder("j3dead", (16, 32), next_bucket, max_n=8)
    assert "J3" in rules(dead)
    escape = audit_bucket_ladder("j3esc", (4,), next_bucket, max_n=8)
    assert "J3" in rules(escape)


def test_j3_quiet_on_tight_ladder():
    from dynamo_tpu.engine.scheduler import next_bucket
    assert not audit_bucket_ladder("j3neg", (4, 8), next_bucket, max_n=8)


def test_j4_flags_host_callback():
    def f(x):
        return jax.pure_callback(
            lambda a: a, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    assert "J4" in rules(trace_and_audit("j4pos", f,
                                         jnp.zeros((4,), jnp.float32)))


def test_j4_quiet_without_callback():
    assert not trace_and_audit("j4neg", lambda x: x + 1,
                               jnp.zeros((4,), jnp.float32))


def test_j5_flags_convert_round_trip():
    found = trace_and_audit(
        "j5pos", lambda x: x.astype(jnp.bfloat16).astype(jnp.float32),
        jnp.zeros((4,), jnp.float32))
    assert "J5" in rules(found)


def test_j5_quiet_when_intermediate_is_used():
    def f(x):
        y = x.astype(jnp.bfloat16)
        return y.astype(jnp.float32), y.sum()

    assert "J5" not in rules(trace_and_audit(
        "j5neg", f, jnp.zeros((4,), jnp.float32)))


# -- baseline mechanics --------------------------------------------------------

def test_baseline_suppresses_by_line_text_not_line_number(tmp_path):
    f1 = Finding(rule="R3", path="a.py", line=10, message="m",
                 line_text="time.sleep(1)")
    path = str(tmp_path / "b.json")
    save_baseline(path, [f1])
    moved = Finding(rule="R3", path="a.py", line=99, message="m",
                    line_text="time.sleep(1)")
    other = Finding(rule="R3", path="a.py", line=11, message="m",
                    line_text="time.sleep(2)")
    fresh = filter_baseline([moved, other], load_baseline(path))
    assert fresh == [other]


def test_baseline_budget_is_per_occurrence(tmp_path):
    f = Finding(rule="R4", path="a.py", line=1, message="m",
                line_text="except:")
    path = str(tmp_path / "b.json")
    save_baseline(path, [f])
    fresh = filter_baseline([f, f], load_baseline(path))
    assert len(fresh) == 1  # budget 1 covers one; the second is new


# -- the repo gate -------------------------------------------------------------

def test_repo_ast_lint_is_clean_vs_baseline():
    """Zero non-baseline AST findings over the whole package: this test
    IS the CI gate for new findings (the committed baseline is empty —
    the tree is clean after the r5 satellite fixes)."""
    findings = run_lint([os.path.join(REPO, "dynamo_tpu")], root=REPO)
    fresh = filter_baseline(findings, load_baseline(BASELINE))
    assert not fresh, "\n".join(f.render() for f in fresh)


def test_repo_jaxpr_audit_is_clean_vs_baseline():
    """Engine entry points (decode window, verify, prefill, paged
    attention, sampler, bucket ladder) trace clean on every invariant."""
    from dynamo_tpu.analysis import audit_engine_entry_points
    findings = audit_engine_entry_points()
    fresh = filter_baseline(findings, load_baseline(BASELINE))
    assert not fresh, "\n".join(f.render() for f in fresh)


def test_baseline_file_is_valid_json():
    with open(BASELINE) as f:
        entries = json.load(f)
    assert isinstance(entries, list)
    for e in entries:
        assert {"rule", "path", "line_text"} <= set(e)


def test_cli_exits_zero_on_clean_tree():
    import subprocess
    import sys
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dynalint.py"),
         "--no-jaxpr"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_round_trips_findings(tmp_path):
    """--json emits findings that reconstruct into Finding objects, and
    exit-code semantics are unchanged by the output format."""
    import subprocess
    import sys
    bad = tmp_path / "frontend"
    bad.mkdir()
    src = textwrap.dedent("""
        async def dispatch(messaging, subject, payload):
            deadline = None
            return await messaging.request(subject, payload,
                                           timeout=deadline)
    """)
    (bad / "leaky.py").write_text(src)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dynalint.py"),
         "--no-jaxpr", "--no-baseline", "--json", str(bad / "leaky.py")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["fresh"] == len(payload["findings"]) >= 1
    revived = [Finding(**d) for d in payload["findings"]]
    assert any(f.rule == "R7" for f in revived)
    assert all(f.line_text for f in revived)


def test_cli_changed_lints_only_the_merge_base_diff():
    """--changed scopes the lint to .py files changed vs the merge-base
    (plus untracked) and stays machine-readable with --json; on the
    current working tree it must agree with the full-tree gate (clean)."""
    import subprocess
    import sys
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dynalint.py"),
         "--changed", "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["fresh"] == 0
    for name in payload.get("files", []):
        assert name.endswith(".py") and ".." not in name
        assert os.path.exists(os.path.join(REPO, name))


# -- layer-3 cost: the memo and the wall-clock bound ---------------------------

def test_flow_layer_rides_the_lint_source_memo(monkeypatch):
    """Repeated passes over an unchanged file are served from the
    content-keyed memo: the flow/CFG solve happens once per (path,
    content), not once per live gate. Proven by making re-parse
    impossible and linting again."""
    from dynamo_tpu.analysis import runner
    src = textwrap.dedent(R21_SRC)
    path = "dynamo_tpu/runtime/memo_fixture.py"
    first = lint_source(src, path)
    assert (path, hash(src)) in runner._LINT_CACHE

    def boom(*a, **k):  # pragma: no cover
        raise AssertionError("memo miss: re-analyzed an unchanged file")

    monkeypatch.setattr(runner.ast, "parse", boom)
    second = lint_source(src, path)
    assert second == first
    assert second is not first  # defensive copy, not the cached list


def test_flow_layer_wall_time_is_bounded():
    """One COLD full-tree pass (memo defeated by a content salt, so
    every file re-runs all rules including the layer-3 CFG/dataflow
    solves) stays a small fraction of the 870s tier-1 budget."""
    import glob
    import time
    files = sorted(glob.glob(os.path.join(REPO, "dynamo_tpu/**/*.py"),
                             recursive=True))
    assert len(files) > 50
    t0 = time.monotonic()
    for path in files:
        rel = os.path.relpath(path, REPO)
        with open(path) as f:
            lint_source(f.read() + "\n# cold-pass salt\n", rel)
    dt = time.monotonic() - t0
    assert dt < 120.0, f"cold full-tree lint took {dt:.1f}s"
