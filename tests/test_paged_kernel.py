"""Pallas decode paged-attention kernel tests (interpret mode on CPU).

The kernel (ops/paged_attention.py) is the decode hot path on real TPU;
interpret mode runs the same program on CPU so correctness is covered
hardware-independently (SURVEY.md §4.5 strategy).
"""
import dataclasses

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.config import EngineConfig, ModelConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.engine.scheduler import SamplingParams
from dynamo_tpu.ops.paged_attention import decode_paged_attention


def test_kernel_matches_oracle():
    rng = np.random.default_rng(0)
    s, h, hkv, hd, p, ps, pb = 3, 8, 4, 32, 16, 8, 4
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    k = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    v = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    page_table = (np.arange(s * pb).reshape(s, pb) * 7) % p
    kv_lens = np.array([5, 17, 32], np.int32)

    out = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(page_table, jnp.int32), jnp.asarray(kv_lens),
        interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, k, v, page_table, kv_lens),
        rtol=1e-5, atol=1e-5)


def _oracle(q, k, v, page_table, kv_lens):
    s, h, hd = q.shape
    hkv = k.shape[0]
    g = h // hkv
    ref = np.zeros_like(q)
    for i in range(s):
        length = kv_lens[i]
        ks = np.concatenate([k[:, pg] for pg in page_table[i]],
                            axis=1)[:, :length]
        vs = np.concatenate([v[:, pg] for pg in page_table[i]],
                            axis=1)[:, :length]
        for head in range(h):
            j = head // g
            scores = (q[i, head] @ ks[j].T) * hd ** -0.5
            probs = np.exp(scores - scores.max())
            probs /= probs.sum()
            ref[i, head] = probs @ vs[j]
    return ref


def test_kernel_hd64_packed_matches_oracle():
    """The flagship shape (llama3-1b: hd=64, ps=64) takes the lane-packed
    DMA path (VERDICT r2 weak #2: the unpacked kernel cannot compile for
    hd<128 on TPU); verify it against the oracle in interpret mode."""
    rng = np.random.default_rng(3)
    s, h, hkv, hd, p, ps, pb = 2, 8, 2, 64, 8, 64, 3
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    k = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    v = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    page_table = ((np.arange(s * pb).reshape(s, pb) * 3) % p).astype(np.int32)
    kv_lens = np.array([70, 128], np.int32)
    out = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(page_table), jnp.asarray(kv_lens), interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, k, v, page_table, kv_lens),
        rtol=1e-5, atol=1e-5)


def test_kernel_hd128_unpacked_matches_oracle():
    """hd=128 (llama3-8b/70b) takes the direct [ps, hd] DMA path."""
    rng = np.random.default_rng(4)
    s, h, hkv, hd, p, ps, pb = 2, 4, 2, 128, 8, 16, 2
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    k = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    v = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    page_table = ((np.arange(s * pb).reshape(s, pb) * 5) % p).astype(np.int32)
    kv_lens = np.array([9, 32], np.int32)
    out = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(page_table), jnp.asarray(kv_lens), interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), _oracle(q, k, v, page_table, kv_lens),
        rtol=1e-5, atol=1e-5)


def test_prefix_kernel_plus_self_matches_oracle():
    """decode_paged_attention_prefix + combine_self_attention (the
    deferred-write hot path) == oracle attention over prefix + new token,
    for hd=64 (packed) and hd=128 (pack=1), including empty prefixes."""
    from dynamo_tpu.ops.paged_attention import (
        combine_self_attention, decode_paged_attention_prefix,
    )
    rng = np.random.default_rng(7)
    for hd in (64, 128):
        s, h, hkv, L, p, ps, pb = 3, 8, 2, 2, 8, 64, 3
        q = rng.standard_normal((s, h, hd)).astype(np.float32)
        kc = rng.standard_normal((L, hkv, p, ps, hd)).astype(np.float32)
        vc = rng.standard_normal((L, hkv, p, ps, hd)).astype(np.float32)
        k_new = rng.standard_normal((s, hkv, hd)).astype(np.float32)
        v_new = rng.standard_normal((s, hkv, hd)).astype(np.float32)
        pt = ((np.arange(s * pb).reshape(s, pb) * 3) % p).astype(np.int32)
        prefix = np.array([70, 0, 130], np.int32)  # incl. empty prefix
        for layer in range(L):
            acc, m, l = decode_paged_attention_prefix(
                jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
                jnp.asarray([layer], jnp.int32), jnp.asarray(pt),
                jnp.asarray(prefix), interpret=True)
            out = combine_self_attention(
                jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
                acc, m, l)
            g = h // hkv
            ref = np.zeros_like(q)
            for i in range(s):
                n = prefix[i]
                ks = np.concatenate([kc[layer][:, pg] for pg in pt[i]],
                                    axis=1)[:, :n]
                vs = np.concatenate([vc[layer][:, pg] for pg in pt[i]],
                                    axis=1)[:, :n]
                for head in range(h):
                    j = head // g
                    kk = np.concatenate([ks[j], k_new[i, j][None]], 0)
                    vv = np.concatenate([vs[j], v_new[i, j][None]], 0)
                    sc = (q[i, head] @ kk.T) * hd ** -0.5
                    pr = np.exp(sc - sc.max())
                    pr /= pr.sum()
                    ref[i, head] = pr @ vv
            np.testing.assert_allclose(np.asarray(out), ref,
                                       rtol=2e-5, atol=2e-5)


def test_kernel_padded_slots_no_nan():
    """kv_len=0 padding slots must produce finite output (clamped to 1)."""
    s, h, hkv, hd, p, ps, pb = 2, 4, 2, 16, 8, 8, 2
    rng = np.random.default_rng(1)
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    k = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    v = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    out = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.zeros((s, pb), jnp.int32),
        jnp.asarray([3, 0], jnp.int32),  # slot 1 is padding
        interpret=True)
    assert np.isfinite(np.asarray(out)).all()


def test_engine_with_kernel_matches_gather_path():
    """Full engine: interpret-mode kernel decode == XLA gather decode."""
    base = ModelConfig(dtype="float32", max_model_len=256)
    ecfg = EngineConfig(page_size=8, num_pages=32, max_slots=2,
                        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                        max_model_len=256)
    prompt = list(range(50, 70))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    off = NativeEngine(dataclasses.replace(base, decode_kernel="off"),
                       ecfg, seed=0).generate(prompt, params, "off")
    kern = NativeEngine(dataclasses.replace(base, decode_kernel="interpret"),
                        ecfg, seed=0).generate(prompt, params, "kern")
    assert off == kern


def test_engine_kernel_sharded_tp2_matches_gather_path():
    """shard_map'd kernel on a tp=2 mesh == gather path on the same mesh.

    Covers VERDICT weak #2: multi-chip meshes must not silently fall back
    to the 2-3x-HBM-traffic XLA gather path."""
    from dynamo_tpu.parallel.mesh import make_mesh

    base = ModelConfig(dtype="float32", max_model_len=256)
    ecfg = EngineConfig(page_size=8, num_pages=32, max_slots=2,
                        max_prefill_chunk=32, prefill_buckets=(8, 16, 32),
                        max_model_len=256)
    mesh = make_mesh(tp=2)
    prompt = list(range(50, 70))
    params = SamplingParams(max_tokens=6, temperature=0.0, ignore_eos=True)
    off = NativeEngine(dataclasses.replace(base, decode_kernel="off"),
                       ecfg, mesh=mesh, seed=0).generate(prompt, params, "off")
    kern = NativeEngine(dataclasses.replace(base, decode_kernel="interpret"),
                        ecfg, mesh=mesh, seed=0).generate(prompt, params, "k")
    assert off == kern


def test_sharded_kernel_matches_single_device():
    """decode_paged_attention_sharded on tp=2/dp=2 == unsharded kernel."""
    from dynamo_tpu.ops.paged_attention import decode_paged_attention_sharded
    from dynamo_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(2)
    s, h, hkv, hd, p, ps, pb = 3, 8, 4, 32, 16, 8, 4
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    k = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    v = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    page_table = ((np.arange(s * pb).reshape(s, pb) * 5) % p).astype(np.int32)
    kv_lens = np.array([7, 20, 32], np.int32)

    ref = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(page_table), jnp.asarray(kv_lens), interpret=True)
    for kwargs in ({"tp": 2}, {"tp": 2, "dp": 2}):
        mesh = make_mesh(**kwargs)
        out = decode_paged_attention_sharded(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(page_table), jnp.asarray(kv_lens), mesh,
            interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_kernel_nonfinite_stale_tail_rows_ignored():
    """Recycled pages leave arbitrary (possibly non-finite) values in the
    boundary page's rows past kv_len; the kernel's zero-probability rows
    must not let them poison the accumulator (0 * NaN = NaN — the
    round-5 page-poisoning class, ops/attention.py got the same fix)."""
    rng = np.random.default_rng(3)
    s, h, hkv, hd, p, ps, pb = 3, 8, 4, 32, 16, 8, 4
    q = rng.standard_normal((s, h, hd)).astype(np.float32)
    k = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    v = rng.standard_normal((hkv, p, ps, hd)).astype(np.float32)
    page_table = (np.arange(s * pb).reshape(s, pb) * 7) % p
    kv_lens = np.array([5, 17, 32], np.int32)

    clean = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(page_table, jnp.int32), jnp.asarray(kv_lens),
        interpret=True)

    # poison every row OUTSIDE each sequence's valid prefix (rows in
    # pages it doesn't own are unread by construction; the dangerous
    # ones are its own boundary-page tail rows)
    k_bad, v_bad = k.copy(), v.copy()
    valid = np.zeros((p * ps,), bool)
    for i in range(s):
        for j in range(int(kv_lens[i])):
            valid[page_table[i, j // ps] * ps + j % ps] = True
    k_bad.reshape(hkv, p * ps, hd)[:, ~valid] = np.nan
    v_bad.reshape(hkv, p * ps, hd)[:, ~valid] = np.nan

    poisoned = decode_paged_attention(
        jnp.asarray(q), jnp.asarray(k_bad), jnp.asarray(v_bad),
        jnp.asarray(page_table, jnp.int32), jnp.asarray(kv_lens),
        interpret=True)
    assert np.isfinite(np.asarray(poisoned)).all()
    np.testing.assert_allclose(np.asarray(poisoned), np.asarray(clean),
                               rtol=1e-5, atol=1e-5)


def test_prefix_kernel_nonfinite_stale_tail_ignored():
    """decode_paged_attention_prefix (the TPU serving decode path) must
    ignore non-finite recycled-page rows past each sequence's prefix —
    the same defense _decode_kernel/_decode_kernel_packed already had
    (ADVICE r5 medium): its per-head loop contracts zero-padded q_shifts
    against ALL 128 lanes, so an unmasked non-finite K lane in a
    NEIGHBOURING token's segment NaNs a VALID token's score, and p == 0
    on masked rows does not survive an unmasked non-finite V."""
    from dynamo_tpu.ops.paged_attention import (
        combine_self_attention, decode_paged_attention_prefix,
    )
    rng = np.random.default_rng(11)
    for hd in (64, 128):  # packed (pack=2) and unpacked (pack=1) paths
        s, h, hkv, L, p, ps, pb = 3, 8, 2, 2, 8, 64, 3
        q = rng.standard_normal((s, h, hd)).astype(np.float32)
        kc = rng.standard_normal((L, hkv, p, ps, hd)).astype(np.float32)
        vc = rng.standard_normal((L, hkv, p, ps, hd)).astype(np.float32)
        k_new = rng.standard_normal((s, hkv, hd)).astype(np.float32)
        v_new = rng.standard_normal((s, hkv, hd)).astype(np.float32)
        pt = ((np.arange(s * pb).reshape(s, pb) * 3) % p).astype(np.int32)
        prefix = np.array([70, 0, 130], np.int32)  # incl. empty prefix

        def run(kc_, vc_, layer):
            acc, m, l = decode_paged_attention_prefix(
                jnp.asarray(q), jnp.asarray(kc_), jnp.asarray(vc_),
                jnp.asarray([layer], jnp.int32), jnp.asarray(pt),
                jnp.asarray(prefix), interpret=True)
            return np.asarray(combine_self_attention(
                jnp.asarray(q), jnp.asarray(k_new), jnp.asarray(v_new),
                acc, m, l))

        # poison every row OUTSIDE every sequence's valid prefix (the
        # dangerous ones are each boundary page's tail rows and the
        # empty-prefix slot's whole allocation)
        valid = np.zeros((p * ps,), bool)
        for i in range(s):
            for j in range(int(prefix[i])):
                valid[pt[i, j // ps] * ps + j % ps] = True
        k_bad, v_bad = kc.copy(), vc.copy()
        k_bad.reshape(L, hkv, p * ps, hd)[:, :, ~valid] = np.nan
        v_bad.reshape(L, hkv, p * ps, hd)[:, :, ~valid] = np.nan

        # one layer suffices: the masking is layer-independent (the layer
        # index only selects which pages the DMA reads) and interpret-mode
        # kernel runs dominate this test's budget
        clean = run(kc, vc, 0)
        poisoned = run(k_bad, v_bad, 0)
        assert np.isfinite(poisoned).all(), f"hd={hd}"
        np.testing.assert_allclose(poisoned, clean, rtol=2e-5, atol=2e-5)
