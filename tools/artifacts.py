"""Evidence-artifact writing policy for bench/profile tools.

VERDICT r5 weak #7: an artifact was captured under one name and renamed
after the fact (`PARITY_TPU_r05.json` -> `PARITY_TPU_r05_initial.json`),
so following the evidence trail required timestamp forensics. Policy,
enforced by routing every evidence write through this module:

- artifacts are written under their FINAL name, directly — never via a
  temp file + rename, never renamed afterwards;
- multi-run artifacts are append-only JSONL (one JSON record per line,
  like tools/tpu_probe_log.jsonl and real_ckpt_e2e's log): re-runs add
  records, they never rewrite history;
- single-record artifacts refuse to silently clobber an existing capture
  (pass overwrite=True only when regenerating the same evidence is the
  point, e.g. a re-run of the same bench round).

Crash-recovery SCRATCH state (bench.py's .bench_state.json) is exempt:
it is consumed by the supervisor within the run and is not evidence, so
its atomic tmp+replace is the right tool there.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict

_TMP_SUFFIXES = (".tmp", ".part", ".partial", "~")


def _check_final_name(path: str) -> None:
    base = os.path.basename(path)
    if base.endswith(_TMP_SUFFIXES) or base.startswith("."):
        raise ValueError(
            f"evidence artifact {path!r} must be written under its final "
            "name (no temp/hidden names — the whole point is that the "
            "name in the log is the name in the repo)")


def append_jsonl(path: str, record: Dict[str, Any]) -> None:
    """Append one JSON record to an append-only evidence log."""
    _check_final_name(path)
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def write_json(path: str, record: Any, overwrite: bool = False) -> None:
    """Write a single-record artifact directly under its final name."""
    _check_final_name(path)
    if not overwrite and os.path.exists(path):
        raise FileExistsError(
            f"evidence artifact {path!r} already exists; artifacts are "
            "written once under their final name — pick a new name for a "
            "new capture, or pass overwrite=True to deliberately "
            "regenerate this one")
    with open(path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
