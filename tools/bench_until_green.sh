#!/bin/sh
# Retry bench.py until it captures a nonzero TPU number, then save the
# result (+ log) as BENCH_SELF_r05.json / .log. The axon tunnel can stall
# for hours; one supervisor run already retries internally (escalating
# per-phase budgets), this loop spans tunnel outages across runs. Every
# supervisor run also appends bare-probe outcomes to
# tools/tpu_probe_log.jsonl — the triage artifact for a zero round.
# Usage: nohup tools/bench_until_green.sh & (repo root; single instance!)
# Exits after MAX_WALL_S (default 9.5 h) even without a capture so the
# driver's own end-of-round bench never finds us holding the one-slot
# tunnel.
cd "$(dirname "$0")/.." || exit 1
start=$(date +%s)
MAX_WALL_S=${MAX_WALL_S:-34200}
i=0
while true; do
  now=$(date +%s)
  if [ $((now - start)) -gt "$MAX_WALL_S" ]; then
    echo "[bench-retry] wall-clock cap reached with no capture; exiting" >&2
    exit 1
  fi
  i=$((i + 1))
  echo "[bench-retry] run $i: $(date -u +%H:%M:%S)" >&2
  rm -f .bench_state.json
  BENCH_BUDGET_S=${BENCH_BUDGET_S:-2400} python bench.py \
      >/tmp/bench_try.json 2>/tmp/bench_try.log
  value=$(python -c "import json;print(json.load(open('/tmp/bench_try.json'))['value'])" \
      2>/dev/null || echo 0)
  case "$value" in
    0|0.0|"")
      fail=$(python -c "import json;print(json.load(open('/tmp/bench_try.json'))['extras'].get('failure',''))" \
          2>/dev/null || echo "?")
      echo "[bench-retry] run $i got no number ($fail); retrying" >&2 ;;
    *)
      stamp=$(date -u +%Y-%m-%dT%H:%M:%SZ)
      python - "$stamp" <<'EOF'
import json, sys
r = json.load(open("/tmp/bench_try.json"))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r05.json", "w"), indent=1)
EOF
      cp /tmp/bench_try.log BENCH_SELF_r05.log
      echo "[bench-retry] captured $value tok/s/chip at $stamp" >&2
      # tunnel is alive: grab the int8 weight-only variant too (the
      # HBM-bandwidth lever; ops/quant.py) — but only if enough of the
      # wall-clock cap remains; holding the one-slot tunnel past the cap
      # could collide with the driver's own end-of-round bench
      now=$(date +%s)
      left=$((MAX_WALL_S - (now - start)))
      if [ "$left" -lt 600 ]; then
        echo "[bench-retry] skipping int8 follow-up (${left}s of wall cap left)" >&2
        exit 0
      fi
      qbudget=${BENCH_BUDGET_S:-2400}
      [ "$left" -lt "$qbudget" ] && qbudget=$((left - 120))
      rm -f .bench_state.json
      BENCH_QUANT=int8 BENCH_BUDGET_S=$qbudget \
          python bench.py >/tmp/bench_q.json 2>/tmp/bench_q.log
      qvalue=$(python -c "import json;print(json.load(open('/tmp/bench_q.json'))['value'])" \
          2>/dev/null || echo 0)
      case "$qvalue" in
        0|0.0|"") echo "[bench-retry] int8 follow-up got no number" >&2 ;;
        *)
          python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" <<'EOF'
import json, sys
r = json.load(open("/tmp/bench_q.json"))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r05_int8.json", "w"), indent=1)
EOF
          cp /tmp/bench_q.log BENCH_SELF_r05_int8.log
          echo "[bench-retry] captured int8 $qvalue tok/s/chip" >&2 ;;
      esac
      exit 0 ;;
  esac
  sleep 60
done
