#!/bin/sh
# Retry bench.py until it captures a nonzero TPU number, then save the
# result (+ log) as BENCH_SELF_r04.json / .log. The axon tunnel can stall
# for hours; one supervisor run already retries internally (escalating
# per-phase budgets), this loop spans tunnel outages across runs.
# Usage: nohup tools/bench_until_green.sh & (repo root; single instance!)
cd "$(dirname "$0")/.." || exit 1
i=0
while true; do
  i=$((i + 1))
  echo "[bench-retry] run $i: $(date -u +%H:%M:%S)" >&2
  rm -f .bench_state.json
  BENCH_BUDGET_S=${BENCH_BUDGET_S:-2400} python bench.py \
      >/tmp/bench_try.json 2>/tmp/bench_try.log
  value=$(python -c "import json;print(json.load(open('/tmp/bench_try.json'))['value'])" \
      2>/dev/null || echo 0)
  case "$value" in
    0|0.0|"") echo "[bench-retry] run $i got no number; retrying" >&2 ;;
    *)
      stamp=$(date -u +%Y-%m-%dT%H:%M:%SZ)
      python - "$stamp" <<'EOF'
import json, sys
r = json.load(open("/tmp/bench_try.json"))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r04.json", "w"), indent=1)
EOF
      cp /tmp/bench_try.log BENCH_SELF_r04.log
      echo "[bench-retry] captured $value tok/s/chip at $stamp" >&2
      exit 0 ;;
  esac
  sleep 60
done
