#!/usr/bin/env python
"""fleet_storm: the resource-telemetry evidence run (FLEET_r10.json).

Produces the full telemetry-plane evidence chain in one run
(docs/OBSERVABILITY.md §7, ISSUE 10 acceptance):

1. **Per-step engine ledger from a live engine**: a tiny CPU engine
   serves a churn of concurrent requests with the ledger on; the
   drained per-step samples are committed as LEDGER_r10.jsonl
   (tools/artifacts.py append-only policy) and summarized in the
   report.
2. **64-worker fleet rollup**: a PR 7 simcluster fleet under a
   FleetRollup scrape loop, per-link KV-transfer bandwidth EWMAs fed
   with seeded samples (the sim has no data plane; a live fleet feeds
   the same TransferCostModel from its transfer backends).
3. **SLO burn-rate fire -> clear**: a seeded storm (lease-expiry kill
   of a fleet fraction + bandwidth collapse on a victim link) drives
   the availability and bandwidth-floor SLOs over their burn
   thresholds; recovery (revive + healthy bandwidth) clears them.
   Alerts ride the event plane (`<ns>.slo.alerts`) and a subscriber
   round-trips them into the artifact.

Contracts (exit 1 on violation): the storm fires at least one alert,
every alert clears after recovery, the event-plane round trip delivers
every alert, and the sim scheduled with zero errors throughout.

Usage:
    python tools/fleet_storm.py                  # full evidence run
    python tools/fleet_storm.py --quick --no-artifact   # shape check
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def run_engine_ledger(jsonl_path: str, quick: bool = False) -> dict:
    """Leg 1: a live engine under churn, ledger drained to JSONL."""
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
    cfg = ModelConfig(dtype="float32", max_model_len=512)
    eng = NativeEngine(cfg, EngineConfig(
        page_size=64, num_pages=32, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512, decode_steps=4,
        pipeline_depth=2), seed=0)
    eng.ledger.configure(enabled=True)
    n_reqs = 3 if quick else 8
    rng = random.Random(10)
    # staggered admissions while decode runs -> the ledger sees all
    # three step kinds (prefill, mixed, decode windows)
    pending = [(f"led{i}",
                [rng.randrange(3, 250) for _ in range(rng.randrange(8, 24))],
                SamplingParams(max_tokens=6 + 2 * (i % 4), temperature=0.0,
                               ignore_eos=True))
               for i in range(n_reqs)]
    eng.add_request(EngineRequest(*pending.pop(0)))
    done = set()
    while eng.has_work() or pending:
        if pending and eng.step_count % 3 == 1:
            eng.add_request(EngineRequest(*pending.pop(0)))
        for ev in eng.step():
            if ev.finished:
                done.add(ev.request_id)
    summary = eng.ledger.summary()
    n = eng.ledger.write_jsonl(jsonl_path)
    eng.close()
    summary.update(jsonl=os.path.basename(jsonl_path), written=n,
                   requests=len(done))
    return summary


async def run_fleet_storm(args) -> dict:
    """Legs 2+3: rollup + SLO fire->clear over a seeded sim storm."""
    import msgpack

    from dynamo_tpu.observability.fleet import FleetRollup, TransferCostModel
    from dynamo_tpu.observability.slo import (
        SloSpec, SloWatchdog, wire_event_plane,
    )
    from dynamo_tpu.observability.timeseries import SeriesStore
    from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig
    interval = 0.1
    sim = await SimCluster(SimConfig(
        workers=args.workers, streams=args.workers * 8,
        lease_ttl_s=0.6, seed=args.seed)).start()
    model = TransferCostModel()
    store = SeriesStore(interval_s=interval, capacity=4096)
    rollup = FleetRollup(sim.client, store=store, interval_s=interval,
                         model=model, expected_workers=args.workers)
    rng = random.Random(args.seed)
    links = sorted(sim.workers)[:8]
    victim = links[0]

    def feed_links(degraded: bool) -> None:
        # seeded per-link samples: ~1 GB/s healthy; the victim link
        # collapses to ~20 MB/s during the storm
        for link in links:
            bw = 2e7 if (degraded and link == victim) \
                else 1e9 * (0.8 + 0.4 * rng.random())
            model.observe(link, int(bw * 0.01), 0.01)

    specs = [
        SloSpec(name="fleet_availability", series="fleet/availability",
                objective=0.85, mode="below", target=0.9,
                short_window_s=1.0, long_window_s=3.0,
                burn_threshold=2.0, min_samples=3),
        SloSpec(name=f"kv_bw_floor/{victim}",
                series=f"link/{victim}/bytes_per_s",
                objective=1e8, mode="below", target=0.9,
                short_window_s=1.0, long_window_s=3.0,
                burn_threshold=2.0, min_samples=3),
        # degraded-exempt: event-plane lag wobbles are sanctioned while
        # the router rides its stale snapshot — this spec must stay
        # quiet even though the storm perturbs the event plane
        SloSpec(name="event_lag", series="cp/event_lag_seconds",
                objective=5.0, mode="above", target=0.9,
                short_window_s=1.0, long_window_s=3.0,
                burn_threshold=2.0, degraded_exempt=True),
    ]
    delivered = []

    async def consume(sub):
        async for _subject, payload in sub:
            delivered.append(msgpack.unpackb(payload, raw=False))

    subject = f"{sim.cfg.namespace}.slo.alerts"
    sub = await sim.plane.messaging.subscribe(subject)
    consumer = asyncio.create_task(consume(sub))
    wd = SloWatchdog(store, specs, degraded_fn=lambda: False)
    wire_event_plane(wd, sim.plane.messaging, subject)

    async def tick(n: int, degraded: bool) -> None:
        for _ in range(n):
            feed_links(degraded)
            await rollup.scrape_once()
            wd.evaluate(time.time())
            await sim.run_load(8)
            await asyncio.sleep(interval)

    report: dict = {"rollup": {}, "slo_states": {}}
    try:
        await tick(args.phase_ticks, degraded=False)
        report["rollup"]["healthy"] = rollup.summary(window_s=5.0)
        report["slo_states"]["healthy"] = wd.summary()
        fired_before = list(wd.firing())

        targets = await sim.kill_fraction(fraction=0.4)
        await tick(args.phase_ticks * 2, degraded=True)
        report["rollup"]["storm"] = rollup.summary(window_s=5.0)
        report["slo_states"]["storm"] = wd.summary()
        firing_in_storm = list(wd.firing())

        await sim.revive(targets)
        # recovery: healthy links + full fleet until every alert clears
        for _ in range(args.phase_ticks * 6):
            await tick(1, degraded=False)
            if not wd.firing():
                break
        report["rollup"]["recovered"] = rollup.summary(window_s=5.0)
        report["slo_states"]["recovered"] = wd.summary()
        await asyncio.sleep(0.2)      # let the last publishes land
    finally:
        consumer.cancel()
        aclose = getattr(sub, "aclose", None)
        if aclose is not None:
            await aclose()
        await sim.stop()

    report["alerts"] = wd.alerts
    report["alerts_delivered"] = delivered
    report["storm"] = {"killed": len(targets), "victim_link": victim,
                       "firing_in_storm": firing_in_storm,
                       "fired_before_storm": fired_before}
    fired = [ev for ev in wd.alerts if ev["event"] == "fire"]
    cleared = [ev for ev in wd.alerts if ev["event"] == "clear"]
    report["contracts"] = {
        "alert_fired_in_storm": bool(firing_in_storm)
        and not fired_before,
        "all_alerts_cleared": not wd.firing()
        and len(cleared) == len(fired) and bool(fired),
        "event_plane_roundtrip": len(delivered) == len(wd.alerts),
        "degraded_exempt_quiet": not any(
            ev["slo"] == "event_lag" for ev in wd.alerts),
        "zero_schedule_errors": sim.schedule_errors == 0,
    }
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet_storm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--workers", type=int, default=64)
    ap.add_argument("--seed", type=int, default=10)
    ap.add_argument("--phase-ticks", type=int, default=15,
                    help="scrape/evaluate ticks per storm phase")
    ap.add_argument("--out", default=os.path.join(REPO_ROOT,
                                                  "FLEET_r10.json"))
    ap.add_argument("--ledger-out",
                    default=os.path.join(REPO_ROOT, "LEDGER_r10.jsonl"))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--no-artifact", action="store_true")
    args = ap.parse_args(argv)
    if args.quick:
        args.workers = min(args.workers, 16)
        args.phase_ticks = min(args.phase_ticks, 8)

    t0 = time.time()
    ledger_path = args.ledger_out if not args.no_artifact \
        else os.path.join("/tmp", "LEDGER_quick.jsonl")
    if os.path.exists(ledger_path) and args.no_artifact:
        os.unlink(ledger_path)
    ledger = run_engine_ledger(ledger_path, quick=args.quick)
    print(f"engine ledger: {json.dumps(ledger)}", flush=True)

    report = asyncio.run(run_fleet_storm(args))
    report["seed"] = args.seed
    report["workers"] = args.workers
    report["ledger"] = ledger
    report["elapsed_s"] = round(time.time() - t0, 1)
    report["ok"] = all(report["contracts"].values())
    print(json.dumps({"contracts": report["contracts"],
                      "alerts": report["alerts"],
                      "elapsed_s": report["elapsed_s"]}, indent=1))
    if not args.no_artifact:
        from tools.artifacts import write_json
        write_json(args.out, report)
        print(f"committed {args.out} (+ {args.ledger_out})",
              file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
