#!/usr/bin/env python
"""fleet_storm: telemetry + autoscaler + multi-tenant QoS evidence runs.

Three evidence modes:

`--mode slo` — the resource-telemetry chain (FLEET_r10.json, ISSUE 10):
engine ledger + 64-worker rollup + SLO fire->clear storm.

`--mode qos` — the multi-tenant QoS chain (QOS_r14.json, ISSUE 14 /
ROADMAP item 5): a seeded BATCH flash crowd arrives under steady
interactive load, driven through the REAL QoS machinery on a virtual
clock — `AdmissionState` (weighted-fair admission, token buckets,
batch-first displacement, class-scaled Retry-After), `StridePicker`
(weighted-deficit queue service with bounded aging), and
`select_victim` (cross-class decode preemption charged against the
preemptor's class budget) — twice (replay) plus a FIFO baseline over
the identical arrival stream. Per-class TTFT series feed the real
`SloWatchdog` with `qos_slo_specs`. Contracts (exit 1 on violation):
interactive p99 TTFT within bound while FIFO's blows through it
(class isolation), batch not starved (aging promotions > 0, every
admitted batch request completes), zero dropped streams across every
preemption, at least one per-class SLO fires AND clears, and the
decision/victim timeline replays bit-identically.

`--mode autoscale` (default) — the closed-loop autoscaler chain
(AUTOSCALE_r12.json, ISSUE 12 / ROADMAP item 4): a seeded diurnal +
flash-crowd traffic shape (`TrafficShape`) driven through the
simcluster's virtual-clock `autoscale_storm` twice — once with the
static prefill/decode split, once with the `FleetAutoscaler` closing
the loop — plus a controller REPLAY run asserting the decision
timeline is bit-identical, and a live-engine `MixedBudgetTuner` leg
showing ledger padding-waste adapting `mixed_token_budget`. Contracts
(exit 1 on violation): the controller holds the TTFT/ITL SLOs the
static split burns through (bad-tick count under half of static's),
zero dropped streams across every re-role drain, zero decisions while
degraded-frozen, zero re-role fence violations, and the replay
timeline matches exactly.

Original telemetry-chain description (ISSUE 10):

1. **Per-step engine ledger from a live engine**: a tiny CPU engine
   serves a churn of concurrent requests with the ledger on; the
   drained per-step samples are committed as LEDGER_r10.jsonl
   (tools/artifacts.py append-only policy) and summarized in the
   report.
2. **64-worker fleet rollup**: a PR 7 simcluster fleet under a
   FleetRollup scrape loop, per-link KV-transfer bandwidth EWMAs fed
   with seeded samples (the sim has no data plane; a live fleet feeds
   the same TransferCostModel from its transfer backends).
3. **SLO burn-rate fire -> clear**: a seeded storm (lease-expiry kill
   of a fleet fraction + bandwidth collapse on a victim link) drives
   the availability and bandwidth-floor SLOs over their burn
   thresholds; recovery (revive + healthy bandwidth) clears them.
   Alerts ride the event plane (`<ns>.slo.alerts`) and a subscriber
   round-trips them into the artifact.

Contracts (exit 1 on violation): the storm fires at least one alert,
every alert clears after recovery, the event-plane round trip delivers
every alert, and the sim scheduled with zero errors throughout.

Usage:
    python tools/fleet_storm.py                  # full evidence run
    python tools/fleet_storm.py --quick --no-artifact   # shape check
"""
from __future__ import annotations

import argparse
import asyncio
import dataclasses
import json
import math
import os
import random
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


@dataclasses.dataclass(frozen=True)
class TrafficShape:
    """Seeded diurnal + flash-crowd traffic driver (requests/second at
    integer virtual-clock ticks). `arrivals(tick)` is a pure function
    of (shape, tick) — the fractional part of the rate resolves through
    a per-tick seeded draw, NOT a stateful rng — so any replay of the
    same shape produces the identical arrival stream regardless of
    what else consumed randomness (the AUTOSCALE_r12 bit-identical
    contract rides on this)."""

    seed: int = 12
    base_rate: float = 5.0        # requests/s at the diurnal midline
    diurnal_amp: float = 0.4      # peak/trough swing fraction
    diurnal_period_s: float = 240.0
    flash_start: int = 100        # flash-crowd window [start, start+len)
    flash_len: int = 60
    flash_mult: float = 2.2

    def rate(self, tick: int) -> float:
        r = self.base_rate * (1.0 + self.diurnal_amp * math.sin(
            2.0 * math.pi * tick / self.diurnal_period_s))
        if self.flash_start <= tick < self.flash_start + self.flash_len:
            r *= self.flash_mult
        return max(0.0, r)

    def arrivals(self, tick: int) -> int:
        r = self.rate(tick)
        n = int(r)
        frac_rng = random.Random(self.seed * 1000003 + tick)
        return n + (1 if frac_rng.random() < r - n else 0)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TrafficShape":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def run_engine_ledger(jsonl_path: str, quick: bool = False) -> dict:
    """Leg 1: a live engine under churn, ledger drained to JSONL."""
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
    cfg = ModelConfig(dtype="float32", max_model_len=512)
    eng = NativeEngine(cfg, EngineConfig(
        page_size=64, num_pages=32, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512, decode_steps=4,
        pipeline_depth=2), seed=0)
    eng.ledger.configure(enabled=True)
    n_reqs = 3 if quick else 8
    rng = random.Random(10)
    # staggered admissions while decode runs -> the ledger sees all
    # three step kinds (prefill, mixed, decode windows)
    pending = [(f"led{i}",
                [rng.randrange(3, 250) for _ in range(rng.randrange(8, 24))],
                SamplingParams(max_tokens=6 + 2 * (i % 4), temperature=0.0,
                               ignore_eos=True))
               for i in range(n_reqs)]
    eng.add_request(EngineRequest(*pending.pop(0)))
    done = set()
    while eng.has_work() or pending:
        if pending and eng.step_count % 3 == 1:
            eng.add_request(EngineRequest(*pending.pop(0)))
        for ev in eng.step():
            if ev.finished:
                done.add(ev.request_id)
    summary = eng.ledger.summary()
    n = eng.ledger.write_jsonl(jsonl_path)
    eng.close()
    summary.update(jsonl=os.path.basename(jsonl_path), written=n,
                   requests=len(done))
    return summary


async def run_fleet_storm(args) -> dict:
    """Legs 2+3: rollup + SLO fire->clear over a seeded sim storm."""
    import msgpack

    from dynamo_tpu.observability.fleet import FleetRollup, TransferCostModel
    from dynamo_tpu.observability.slo import (
        SloSpec, SloWatchdog, wire_event_plane,
    )
    from dynamo_tpu.observability.timeseries import SeriesStore
    from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig
    interval = 0.1
    sim = await SimCluster(SimConfig(
        workers=args.workers, streams=args.workers * 8,
        lease_ttl_s=0.6, seed=args.seed)).start()
    model = TransferCostModel()
    store = SeriesStore(interval_s=interval, capacity=4096)
    rollup = FleetRollup(sim.client, store=store, interval_s=interval,
                         model=model, expected_workers=args.workers)
    rng = random.Random(args.seed)
    links = sorted(sim.workers)[:8]
    victim = links[0]

    def feed_links(degraded: bool) -> None:
        # seeded per-link samples: ~1 GB/s healthy; the victim link
        # collapses to ~20 MB/s during the storm
        for link in links:
            bw = 2e7 if (degraded and link == victim) \
                else 1e9 * (0.8 + 0.4 * rng.random())
            model.observe(link, int(bw * 0.01), 0.01)

    specs = [
        SloSpec(name="fleet_availability", series="fleet/availability",
                objective=0.85, mode="below", target=0.9,
                short_window_s=1.0, long_window_s=3.0,
                burn_threshold=2.0, min_samples=3),
        SloSpec(name=f"kv_bw_floor/{victim}",
                series=f"link/{victim}/bytes_per_s",
                objective=1e8, mode="below", target=0.9,
                short_window_s=1.0, long_window_s=3.0,
                burn_threshold=2.0, min_samples=3),
        # degraded-exempt: event-plane lag wobbles are sanctioned while
        # the router rides its stale snapshot — this spec must stay
        # quiet even though the storm perturbs the event plane
        SloSpec(name="event_lag", series="cp/event_lag_seconds",
                objective=5.0, mode="above", target=0.9,
                short_window_s=1.0, long_window_s=3.0,
                burn_threshold=2.0, degraded_exempt=True),
    ]
    delivered = []

    async def consume(sub):
        async for _subject, payload in sub:
            delivered.append(msgpack.unpackb(payload, raw=False))

    subject = f"{sim.cfg.namespace}.slo.alerts"
    sub = await sim.plane.messaging.subscribe(subject)
    consumer = asyncio.create_task(consume(sub))
    wd = SloWatchdog(store, specs, degraded_fn=lambda: False)
    wire_event_plane(wd, sim.plane.messaging, subject)

    async def tick(n: int, degraded: bool) -> None:
        for _ in range(n):
            feed_links(degraded)
            await rollup.scrape_once()
            wd.evaluate(time.time())
            await sim.run_load(8)
            await asyncio.sleep(interval)

    report: dict = {"rollup": {}, "slo_states": {}}
    try:
        await tick(args.phase_ticks, degraded=False)
        report["rollup"]["healthy"] = rollup.summary(window_s=5.0)
        report["slo_states"]["healthy"] = wd.summary()
        fired_before = list(wd.firing())

        targets = await sim.kill_fraction(fraction=0.4)
        await tick(args.phase_ticks * 2, degraded=True)
        report["rollup"]["storm"] = rollup.summary(window_s=5.0)
        report["slo_states"]["storm"] = wd.summary()
        firing_in_storm = list(wd.firing())

        await sim.revive(targets)
        # recovery: healthy links + full fleet until every alert clears
        for _ in range(args.phase_ticks * 6):
            await tick(1, degraded=False)
            if not wd.firing():
                break
        report["rollup"]["recovered"] = rollup.summary(window_s=5.0)
        report["slo_states"]["recovered"] = wd.summary()
        await asyncio.sleep(0.2)      # let the last publishes land
    finally:
        consumer.cancel()
        aclose = getattr(sub, "aclose", None)
        if aclose is not None:
            await aclose()
        await sim.stop()

    report["alerts"] = wd.alerts
    report["alerts_delivered"] = delivered
    report["storm"] = {"killed": len(targets), "victim_link": victim,
                       "firing_in_storm": firing_in_storm,
                       "fired_before_storm": fired_before}
    fired = [ev for ev in wd.alerts if ev["event"] == "fire"]
    cleared = [ev for ev in wd.alerts if ev["event"] == "clear"]
    report["contracts"] = {
        "alert_fired_in_storm": bool(firing_in_storm)
        and not fired_before,
        "all_alerts_cleared": not wd.firing()
        and len(cleared) == len(fired) and bool(fired),
        "event_plane_roundtrip": len(delivered) == len(wd.alerts),
        "degraded_exempt_quiet": not any(
            ev["slo"] == "event_lag" for ev in wd.alerts),
        "zero_schedule_errors": sim.schedule_errors == 0,
    }
    return report


def run_budget_tuner(quick: bool = False) -> dict:
    """The item-4 local self-tuning leg: a live tiny engine whose
    ledger padding-waste drives `MixedBudgetTuner` adjustments of
    `mixed_token_budget` (virtual-clock ticks between step batches).
    Same engine geometry as `run_engine_ledger` so the jitted programs
    hit the persistent XLA cache."""
    from dynamo_tpu.engine.config import EngineConfig, ModelConfig
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
    from dynamo_tpu.runtime.autoscaler import AutoscalerStats, MixedBudgetTuner
    cfg = ModelConfig(dtype="float32", max_model_len=512)
    eng = NativeEngine(cfg, EngineConfig(
        page_size=64, num_pages=32, max_slots=4, max_prefill_chunk=32,
        prefill_buckets=(8, 16, 32), max_model_len=512, decode_steps=4,
        pipeline_depth=2), seed=0)
    eng.ledger.configure(enabled=True)
    stats = AutoscalerStats()
    tuner = MixedBudgetTuner(eng.scheduler, eng.ledger,
                             min_tokens=64, cooldown_s=4.0,
                             hysteresis_ticks=2, stats=stats)
    budget0 = eng.scheduler.mixed_token_budget
    rng = random.Random(13)
    # tiny staggered prompts under the default (oversized) budget: the
    # [Bb, Tb] buckets charge far more padding than useful tokens, so
    # the windowed waste fraction sits over pad_hi and the tuner walks
    # the budget down
    n_reqs = 4 if quick else 10
    pending = [(f"tune{i}",
                [rng.randrange(3, 250) for _ in range(rng.randrange(6, 18))],
                SamplingParams(max_tokens=5 + (i % 4), temperature=0.0,
                               ignore_eos=True))
               for i in range(n_reqs)]
    eng.add_request(EngineRequest(*pending.pop(0)))
    vts = 0.0
    while eng.has_work() or pending:
        if pending and eng.step_count % 3 == 1:
            eng.add_request(EngineRequest(*pending.pop(0)))
        for _ in eng.step():
            pass
        vts += 2.5                 # virtual seconds per engine step
        tuner.tick(vts)
    final = eng.scheduler.mixed_token_budget
    pad = eng.ledger.pad_fraction()
    eng.close()
    return {"budget_initial": budget0, "budget_final": final,
            "adjustments": tuner.adjustments,
            "n_adjustments": stats.budget_adjustments,
            "pad_waste_frac": round(pad, 4)}


async def run_autoscale_storm(args) -> dict:
    """The AUTOSCALE_r12 evidence chain: static vs controller vs
    controller-replay over the identical seeded plan."""
    from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig
    traffic = TrafficShape(seed=args.seed + 1)
    degraded_window = (args.degraded_start,
                       args.degraded_start + args.degraded_len)

    async def one_run(controller: bool) -> dict:
        sim = await SimCluster(SimConfig(
            workers=args.autoscale_workers,
            streams=args.autoscale_workers * 8,
            lease_ttl_s=30.0,       # virtual storm: no expiry churn leg
            seed=args.seed)).start()
        try:
            return await sim.autoscale_storm(
                traffic, ticks=args.ticks, controller=controller,
                degraded_window=degraded_window)
        finally:
            await sim.stop()

    static = await one_run(False)
    ctrl = await one_run(True)
    replay = await one_run(True)

    deg_len = degraded_window[1] - degraded_window[0]
    contracts = {
        # the static 8+8 split genuinely burns through the TTFT SLO...
        "static_split_burns":
            static["slo"]["ttft_bad_ticks"] >= 10,
        # ...and the controller holds it (less than half the bad ticks)
        # without trading it for ITL burn
        "controller_holds_ttft":
            ctrl["slo"]["ttft_bad_ticks"]
            <= max(2, static["slo"]["ttft_bad_ticks"] // 2),
        "controller_holds_itl":
            ctrl["slo"]["itl_bad_ticks"]
            <= static["slo"]["itl_bad_ticks"] + 2,
        "controller_acted": len(ctrl["controller"]["timeline"]) >= 2,
        "zero_dropped_streams":
            static["streams"]["dropped"] == 0
            and ctrl["streams"]["dropped"] == 0
            and replay["streams"]["dropped"] == 0,
        "zero_decisions_while_degraded":
            ctrl["decisions_in_degraded"] == 0
            and ctrl["controller"]["frozen_degraded"] == deg_len,
        "zero_fence_violations":
            ctrl["fence_violations"] == 0
            and replay["fence_violations"] == 0,
        # bit-identical replay: the whole decision timeline, not a hash
        "replay_bit_identical":
            replay["controller"]["timeline"]
            == ctrl["controller"]["timeline"],
    }
    return {
        "traffic": traffic.to_dict(),
        "ticks": args.ticks,
        "workers": args.autoscale_workers,
        "seed": args.seed,
        "degraded_window": list(degraded_window),
        "static": static,
        "controller": ctrl,
        "replay_timeline_len": len(replay["controller"]["timeline"]),
        "contracts": contracts,
    }


@dataclasses.dataclass(frozen=True)
class TenantShape:
    """Seeded multi-tenant arrival driver: steady interactive +
    standard load, a BATCH flash crowd in [crowd_start, crowd_start +
    crowd_len). `arrivals(cls, tick)` is a pure function of (shape,
    class, tick) — per-tick seeded draws, no stateful rng — so the
    QOS_r14 bit-identical-replay contract holds regardless of what
    else consumes randomness."""

    seed: int = 14
    interactive_rate: float = 3.0
    standard_rate: float = 1.2
    batch_rate: float = 0.6
    crowd_start: int = 30
    crowd_len: int = 40
    crowd_mult: float = 14.0

    def rate(self, cls: str, tick: int) -> float:
        r = {"interactive": self.interactive_rate,
             "standard": self.standard_rate,
             "batch": self.batch_rate}[cls]
        if cls == "batch" and \
                self.crowd_start <= tick < self.crowd_start + self.crowd_len:
            r *= self.crowd_mult
        return r

    def arrivals(self, cls: str, tick: int) -> int:
        r = self.rate(cls, tick)
        n = int(r)
        # zlib.crc32, not hash(): str hashing is per-process randomized
        # and would break the cross-process bit-identical replay
        import zlib
        rng = random.Random((self.seed * 1000003 + tick) * 131
                            + zlib.crc32(cls.encode()) % 9973)
        return n + (1 if rng.random() < r - n else 0)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "TenantShape":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


def qos_storm_once(shape: TenantShape, qos_on: bool, ticks: int = 220,
                   max_inflight: int = 60, max_queued: int = 20,
                   prefill_tok_s: float = 2600.0, decode_slots: int = 18,
                   decode_tok_s: float = 30.0,
                   aging_limit: int = 8, drain_ticks: int = 30) -> dict:
    """One virtual-clock multi-tenant storm through the REAL QoS
    machinery (runtime/qos.py): AdmissionState at the door, a
    StridePicker ordering prefill service AND decode-slot grants
    (weighted deficit, bounded aging — the no-starvation guarantee the
    aging_promotions counters evidence), select_victim for cross-class
    decode preemption charged against the preemptor's class budget.
    qos_on=False collapses the policy to one class — the FIFO baseline
    over the IDENTICAL seeded arrival stream.

    Virtual service model (pure): prefill drains admitted requests at
    `prefill_tok_s` in picker order; a completed prefill wants a
    decode slot — TTFT = slot-acquisition tick + 1 - arrival — and a
    blocked high-class request preempts the lowest-class decode
    (progress retained: the victim resumes from its committed tokens,
    never restarts, never drops). Per-class TTFT p95 series feed the
    real SloWatchdog via qos_slo_specs.
    """
    from dynamo_tpu.observability.slo import SloWatchdog, qos_slo_specs
    from dynamo_tpu.observability.timeseries import SeriesStore
    from dynamo_tpu.runtime.qos import (
        AdmissionState, QosClass, QosPolicy, StridePicker, select_victim,
    )
    if qos_on:
        policy = QosPolicy((
            QosClass("interactive", priority=2, weight=8.0,
                     ttft_target_s=3.0, itl_target_s=1.0,
                     preempt_budget=4, latency_weight=2.0),
            QosClass("standard", priority=1, weight=3.0,
                     ttft_target_s=8.0, itl_target_s=1.0,
                     preempt_budget=1),
            QosClass("batch", priority=0, weight=1.0,
                     ttft_target_s=12.0, itl_target_s=2.0,
                     # rate budget: the flash crowd overruns the batch
                     # token bucket and sheds batch-first at the door;
                     # sized so the admitted batch backlog drains (and
                     # its TTFT SLO clears) well inside the run
                     rate_per_s=2.5, burst=6.0),
        ), default="standard", aging_limit=aging_limit)
    else:
        policy = QosPolicy((QosClass("standard", priority=1,
                                     weight=1.0, ttft_target_s=8.0),),
                           default="standard", aging_limit=aging_limit)
    classes = ("interactive", "standard", "batch")

    def label(cls: str) -> str:
        return policy.resolve(cls).name   # FIFO folds all -> standard

    adm = AdmissionState(policy, max_inflight, max_queued)
    prefill_pick = StridePicker(policy)
    decode_pick = StridePicker(policy)

    class VStream:
        __slots__ = ("rid", "cls", "qos", "t_arr", "prefill_left",
                     "decode_left", "num_computed", "preempted",
                     "ttft", "done_at")

        def __init__(self, rid, cls, t_arr, prefill, decode):
            self.rid, self.cls, self.t_arr = rid, cls, t_arr
            self.qos = label(cls)       # select_victim reads .qos
            self.prefill_left = prefill
            self.decode_left = decode
            self.num_computed = 0
            self.preempted = 0
            self.ttft = None
            self.done_at = None

    store = SeriesStore(interval_s=1.0, capacity=max(600, ticks + 8))
    wd = SloWatchdog(store, qos_slo_specs(
        policy, short_window_s=8.0, long_window_s=24.0, min_samples=3),
        degraded_fn=lambda: False)
    timeline = []               # the bit-identical-replay contract
    adm_waiting = {}            # cls -> [VStream] (admission queue)
    prefill_q = {}              # cls -> [VStream] (admitted, prefilling)
    decode_wait = {}            # cls -> [VStream] (prefilled, want slot)
    running = [None] * decode_slots
    preempt_debt = {}
    stats = {c: {"arrived": 0, "admitted": 0, "shed": 0, "done": 0,
                 "preempted": 0, "ttfts": []} for c in classes}
    dropped = 0
    rid_seq = 0
    ttft_window = {c: [] for c in classes}

    def shed(s, cls_name):
        stats[s.cls]["shed"] += 1
        timeline.append([tick, "shed", s.rid, label(s.cls)])

    def enter_prefill(s):
        prefill_q.setdefault(label(s.cls), []).append(s)
        stats[s.cls]["admitted"] += 1

    def take_slot(s, slot):
        running[slot] = s
        if s.ttft is None:
            s.ttft = tick + 1.0 - s.t_arr
            stats[s.cls]["ttfts"].append(s.ttft)
            w = ttft_window[s.cls]
            w.append(s.ttft)
            del w[:-10]    # sliding p95 window: short enough that
            #                post-crowd recovery shows within the run
        if s.preempted and preempt_debt.get(s.preempted, 0):
            # victim resumed: repay the preemptor class's debt (the
            # budget bounds OUTSTANDING displacements)
            n = preempt_debt[s.preempted]
            if n > 1:
                preempt_debt[s.preempted] = n - 1
            else:
                preempt_debt.pop(s.preempted, None)
            s.preempted = 0

    for tick in range(ticks):
        ts = float(tick)
        # 1. arrivals -> admission (real AdmissionState); the last
        # drain_ticks take no arrivals so the completion contracts
        # (batch done == admitted, zero drops) evaluate a drained system
        for cls in classes if tick < ticks - drain_ticks else ():
            for _ in range(shape.arrivals(cls, tick)):
                rid_seq += 1
                rng = random.Random(shape.seed * 7919 + rid_seq)
                s = VStream(rid_seq, cls, ts,
                            rng.randint(150, 500), rng.randint(40, 140))
                stats[cls]["arrived"] += 1
                d = adm.try_admit(label(cls), now=ts)
                if d.kind == "admit":
                    enter_prefill(s)
                elif d.kind == "shed":
                    shed(s, label(cls))
                else:
                    if d.kind == "displace":
                        vic_q = adm_waiting.get(d.victim_class, [])
                        if vic_q:
                            vic = vic_q.pop()       # newest sheds first
                            shed(vic, d.victim_class)
                            timeline.append([tick, "displace",
                                             d.victim_class])
                    adm_waiting.setdefault(label(s.cls), []).append(s)
        # 2. prefill service: weighted-deficit class order (bounded
        # aging: a backlogged batch class skipped aging_limit rounds is
        # served next — no starvation, the R19 bound)
        capacity = prefill_tok_s
        while capacity > 0:
            backlog = [c for c, q in prefill_q.items() if q]
            order = prefill_pick.order(backlog)
            if not order:
                break
            cls = order[0]
            before = prefill_pick.aging_promotions
            prefill_pick.charge(cls, backlog)
            if prefill_pick.aging_promotions > before:
                timeline.append([tick, "aging", cls])
            s = prefill_q[cls][0]
            take = min(s.prefill_left, capacity)
            s.prefill_left -= take
            capacity -= take
            if s.prefill_left <= 0:
                prefill_q[cls].pop(0)
                decode_wait.setdefault(cls, []).append(s)
        # 3. decode-slot assignment: free slots first (weighted-fair
        # with aging), then cross-class preemption for still-blocked
        # high classes (select_victim: lowest class, youngest within;
        # victim starvation bounded by class-band requeue + aging)
        while any(x is None for x in running):
            backlog = [c for c, q in decode_wait.items() if q]
            order = decode_pick.order(backlog)
            if not order:
                break
            cls = order[0]
            before = decode_pick.aging_promotions
            decode_pick.charge(cls, backlog)
            if decode_pick.aging_promotions > before:
                timeline.append([tick, "aging", cls])
            take_slot(decode_wait[cls].pop(0), running.index(None))
        if qos_on:
            for cls in sorted((c for c, q in decode_wait.items() if q),
                              key=lambda c: -policy.priority_of(c)):
                c_obj = policy.resolve(cls)
                while decode_wait[cls]:
                    if c_obj.preempt_budget <= 0 or \
                            preempt_debt.get(cls, 0) \
                            >= c_obj.preempt_budget:
                        break
                    victim = select_victim(
                        running, policy,
                        below_prio=c_obj.priority)
                    if victim is None:
                        break
                    slot = running.index(victim)
                    running[slot] = None
                    victim.preempted = cls       # debt owner
                    preempt_debt[cls] = preempt_debt.get(cls, 0) + 1
                    stats[victim.cls]["preempted"] += 1
                    # committed-prefix semantics: progress retained,
                    # victim rejoins the head of its class band
                    decode_wait.setdefault(label(victim.cls),
                                           []).insert(0, victim)
                    s = decode_wait[cls].pop(0)
                    take_slot(s, slot)
                    timeline.append([tick, "preempt", s.rid, victim.rid,
                                     cls, label(victim.cls)])
        # 4. decode progress + completion -> admission release/grant
        for i, s in enumerate(running):
            if s is None:
                continue
            s.decode_left -= decode_tok_s
            s.num_computed += decode_tok_s
            if s.decode_left <= 0:
                running[i] = None
                s.done_at = ts
                stats[s.cls]["done"] += 1
                adm.note_released(label(s.cls))
                g = adm.grant()
                if g is not None:
                    q = adm_waiting.get(g, [])
                    if q:
                        adm.note_granted(g)
                        enter_prefill(q.pop(0))
                    else:
                        adm.note_abandoned(g)
        # 5. per-class series + watchdog
        for cls in classes:
            w = ttft_window[cls]
            if w:
                store.record(f"qos/{label(cls)}/ttft_p95",
                             percentile(sorted(w), 0.95), ts)
        wd.evaluate(ts)

    lat = {c: sorted(stats[c]["ttfts"]) for c in classes}
    return {
        "mode": "qos" if qos_on else "fifo",
        "ticks": ticks,
        "requests": rid_seq,
        "per_class": {
            c: {
                "arrived": stats[c]["arrived"],
                "admitted": stats[c]["admitted"],
                "done": stats[c]["done"],
                "shed": stats[c]["shed"],
                "preempted": stats[c]["preempted"],
                "ttft_p50_s": round(percentile(lat[c], 0.5), 3),
                "ttft_p99_s": round(percentile(lat[c], 0.99), 3),
            } for c in classes},
        "aging_promotions": (prefill_pick.aging_promotions
                             + decode_pick.aging_promotions),
        "admission_displaced": adm.displaced,
        "dropped_streams": dropped,
        "slo_alerts": list(wd.alerts),
        "slo_firing_at_end": wd.firing(),
        "timeline": timeline,
    }


def percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return float(sorted_vals[i])


def run_qos_storm(args) -> dict:
    """The QOS_r14 evidence chain: QoS vs FIFO over the identical
    seeded multi-tenant burst, plus a bit-identical replay."""
    shape = TenantShape(seed=args.seed + 4)
    kw = dict(ticks=args.ticks)
    qos = qos_storm_once(shape, True, **kw)
    fifo = qos_storm_once(shape, False, **kw)
    replay = qos_storm_once(shape, True, **kw)

    pc = qos["per_class"]
    fired = [ev for ev in qos["slo_alerts"] if ev["event"] == "fire"
             and ev["slo"].startswith(("ttft_p95/", "itl_p99/"))]
    cleared = [ev for ev in qos["slo_alerts"] if ev["event"] == "clear"]
    contracts = {
        # class isolation: interactive p99 TTFT bound held under the
        # batch flash crowd, while FIFO over the SAME arrivals burns it
        "interactive_p99_held":
            pc["interactive"]["ttft_p99_s"] <= args.interactive_bound_s,
        "fifo_burns_interactive":
            fifo["per_class"]["interactive"]["ttft_p99_s"]
            > 2 * pc["interactive"]["ttft_p99_s"],
        # no starvation: the bounded-aging guarantee actually engaged,
        # and every admitted batch request completed
        "batch_not_starved":
            qos["aging_promotions"] > 0
            and pc["batch"]["done"] == pc["batch"]["admitted"],
        # preemption never drops: victims resume from committed
        # progress and finish
        "zero_dropped_streams":
            qos["dropped_streams"] == 0
            and sum(c["done"] for c in pc.values())
            == sum(c["admitted"] for c in pc.values()),
        "preemptions_happened":
            sum(c["preempted"] for c in pc.values()) >= 1,
        # batch sheds first at the door (rate budget + displacement)
        "batch_sheds_first":
            pc["batch"]["shed"] > 0
            and pc["interactive"]["shed"] == 0,
        # at least one per-class SloSpec fired AND cleared in-storm
        "per_class_slo_fired_and_cleared":
            bool(fired) and len(cleared) >= len(fired)
            and not qos["slo_firing_at_end"],
        # the whole decision/victim timeline, not a hash
        "replay_bit_identical": replay["timeline"] == qos["timeline"],
    }
    return {
        "shape": shape.to_dict(),
        "ticks": args.ticks,
        "seed": args.seed,
        "interactive_bound_s": args.interactive_bound_s,
        "qos": qos,
        "fifo": fifo,
        "replay_timeline_len": len(replay["timeline"]),
        "contracts": contracts,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet_storm", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--mode", choices=("autoscale", "slo", "qos"),
                    default="autoscale")
    ap.add_argument("--interactive-bound-s", type=float, default=3.0,
                    help="qos mode: interactive p99 TTFT contract bound "
                         "(virtual seconds)")
    ap.add_argument("--workers", type=int, default=64,
                    help="fleet size for the slo-mode storm")
    ap.add_argument("--seed", type=int, default=10)
    ap.add_argument("--phase-ticks", type=int, default=15,
                    help="scrape/evaluate ticks per slo storm phase")
    ap.add_argument("--ticks", type=int, default=360,
                    help="virtual seconds of the autoscale storm")
    ap.add_argument("--autoscale-workers", type=int, default=16,
                    help="fleet size of the autoscale storm (8+8 split)")
    ap.add_argument("--degraded-start", type=int, default=210)
    ap.add_argument("--degraded-len", type=int, default=30)
    ap.add_argument("--out", default=None,
                    help="artifact path (default FLEET_r10.json / "
                         "AUTOSCALE_r12.json by mode)")
    ap.add_argument("--ledger-out",
                    default=os.path.join(REPO_ROOT, "LEDGER_r10.jsonl"))
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--no-artifact", action="store_true")
    args = ap.parse_args(argv)
    if args.quick:
        args.workers = min(args.workers, 16)
        args.phase_ticks = min(args.phase_ticks, 8)
        args.ticks = min(args.ticks, 240)

    t0 = time.time()
    if args.mode == "qos":
        if args.ticks > 240:
            args.ticks = 220        # qos storm is sized for ~220 ticks
        out = args.out or os.path.join(REPO_ROOT, "QOS_r14.json")
        report = run_qos_storm(args)
        report["elapsed_s"] = round(time.time() - t0, 1)
        report["ok"] = all(report["contracts"].values())
        print(json.dumps({
            "contracts": report["contracts"],
            "qos_per_class": report["qos"]["per_class"],
            "fifo_interactive_p99":
                report["fifo"]["per_class"]["interactive"]["ttft_p99_s"],
            "aging_promotions": report["qos"]["aging_promotions"],
            "timeline_len": len(report["qos"]["timeline"]),
            "slo_alerts": report["qos"]["slo_alerts"],
            "elapsed_s": report["elapsed_s"]}, indent=1))
        if not args.no_artifact:
            from tools.artifacts import write_json
            write_json(out, report)
            print(f"committed {out}", file=sys.stderr)
        return 0 if report["ok"] else 1
    if args.mode == "autoscale":
        out = args.out or os.path.join(REPO_ROOT, "AUTOSCALE_r12.json")
        report = asyncio.run(run_autoscale_storm(args))
        report["budget_tuning"] = run_budget_tuner(quick=args.quick)
        report["contracts"]["budget_tuner_adjusted"] = \
            report["budget_tuning"]["n_adjustments"] >= 1
        report["elapsed_s"] = round(time.time() - t0, 1)
        report["ok"] = all(report["contracts"].values())
        print(json.dumps({
            "contracts": report["contracts"],
            "static_ttft_bad_ticks":
                report["static"]["slo"]["ttft_bad_ticks"],
            "controller_ttft_bad_ticks":
                report["controller"]["slo"]["ttft_bad_ticks"],
            "decisions": report["controller"]["controller"]["timeline"],
            "budget_tuning": report["budget_tuning"],
            "elapsed_s": report["elapsed_s"]}, indent=1))
        if not args.no_artifact:
            from tools.artifacts import write_json
            write_json(out, report)
            print(f"committed {out}", file=sys.stderr)
        return 0 if report["ok"] else 1

    out = args.out or os.path.join(REPO_ROOT, "FLEET_r10.json")
    ledger_path = args.ledger_out if not args.no_artifact \
        else os.path.join("/tmp", "LEDGER_quick.jsonl")
    if os.path.exists(ledger_path) and args.no_artifact:
        os.unlink(ledger_path)
    ledger = run_engine_ledger(ledger_path, quick=args.quick)
    print(f"engine ledger: {json.dumps(ledger)}", flush=True)

    report = asyncio.run(run_fleet_storm(args))
    report["seed"] = args.seed
    report["workers"] = args.workers
    report["ledger"] = ledger
    report["elapsed_s"] = round(time.time() - t0, 1)
    report["ok"] = all(report["contracts"].values())
    print(json.dumps({"contracts": report["contracts"],
                      "alerts": report["alerts"],
                      "elapsed_s": report["elapsed_s"]}, indent=1))
    if not args.no_artifact:
        from tools.artifacts import write_json
        write_json(out, report)
        print(f"committed {out} (+ {args.ledger_out})",
              file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
