#!/bin/sh
# Tunnel-window watcher: the axon tunnel flaps (minutes-long up-windows
# between hours of outage — TRIAGE_r05.md). This loop probes it and, the
# moment a probe answers, runs the remaining TPU-evidence items in
# priority order, each gated on a marker artifact so completed items are
# never redone:
#   1. PARITY_TPU_r05.json      — tools/tpu_parity_quick.py (window vs
#                                 single-step greedy, token-for-token)
#   2. real_ckpt_e2e_tpu.log    — tools/real_ckpt_e2e.py on the TPU
#                                 backend (full-stack HTTP serve of a
#                                 genuine HF checkpoint, transformers
#                                 oracle)
#   3. BENCH_SELF_r05_int8.json — BENCH_QUANT=int8 bench.py (weight-only
#                                 int8: the HBM-bandwidth lever)
# Single-slot tunnel: waits for any bench_until_green.sh / bench.py to
# exit before touching it. Usage: nohup tools/tpu_window_watch.sh &
cd "$(dirname "$0")/.." || exit 1
start=$(date +%s)
MAX_WALL_S=${MAX_WALL_S:-30600}
while true; do
  now=$(date +%s)
  [ $((now - start)) -gt "$MAX_WALL_S" ] && { echo "[watch] wall cap; exit" >&2; exit 0; }
  if [ -e PARITY_TPU_r05.json ] && [ -e real_ckpt_e2e_tpu.log ] \
      && [ -e BENCH_SELF_r05_int8.json ] \
      && [ -e BENCH_SELF_r05_w128.json ] \
      && [ -e BENCH_SELF_r05_spec.json ] \
      && [ -e PARITY_TPU_r06_int8.json ] \
      && [ -e BENCH_SELF_r06_int8_churn.json ] \
      && [ -e PARITY_TPU_r06_kvq.json ] \
      && [ -e BENCH_SELF_r06_kvq.json ] \
      && [ -e BENCH_SELF_r11_overlap_tpu.json ] \
      && [ -e BENCH_SELF_r13_warm_prefix_tpu.json ] \
      && [ -e BENCH_SELF_r15_sharded_tpu.json ] \
      && [ -e BENCH_SELF_r17_pool_remote_tpu.json ] \
      && [ -e PARITY_TPU_r18_ragged.json ] \
      && [ -e BENCH_SELF_r18_ragged_tpu.json ] \
      && [ -e BENCH_SELF_r19_failslow_tpu.json ] \
      && [ -e BENCH_SELF_r20_long_context_tpu.json ]; then
    echo "[watch] all TPU evidence captured; exiting" >&2
    exit 0
  fi
  # one-slot tunnel: never probe while another bench holds it. Patterns
  # must match actual INVOCATIONS, not any process whose argv merely
  # mentions the filename (the round driver's prompt text contains
  # "bench.py", which a bare `pgrep -f bench.py` matches — that blinded
  # this watcher for a whole session).
  # [b]racket trick: the pattern never matches its own pgrep process.
  # Three patterns so any invocation spelling is caught: the retry loop
  # by filename, a supervisor by interpreter+script adjacency, and the
  # worker child by its --worker flag (always spawned with an absolute
  # path, so it backstops exotic supervisor spellings).
  if pgrep -f "^([^ ]*/)?(sh|bash) ([^ ]*/)?bench_until_green\.sh" >/dev/null 2>&1 \
      || pgrep -f "^([^ ]*/)?python[^ ]* ([^ ]*/)?bench\.py" >/dev/null 2>&1; then
    sleep 60
    continue
  fi
  probe=$(timeout 75 python -c "
import json, time
t = time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())
import jax
ds = jax.devices()
print(json.dumps({'t': t, 'ok': jax.default_backend() == 'tpu', 'n': len(ds)}))
" 2>/dev/null | tail -1)
  echo "{\"t\": \"$(date -u +%Y-%m-%dT%H:%M:%SZ)\", \"watch\": true, \"probe\": ${probe:-null}}" \
      >> tools/tpu_probe_log.jsonl
  case "$probe" in
    *'"ok": true'*)
      echo "[watch] tunnel UP at $(date -u +%H:%M:%S); running evidence items" >&2
      if [ ! -e PARITY_TPU_r05.json ]; then
        # first capture (PARITY_TPU_r05_initial.json) DIVERGED@39 with no
        # attribution; the tool now adds a top-2 margin probe — recapture
        echo "[watch] -> parity" >&2
        timeout 900 python tools/tpu_parity_quick.py >> tpu_parity_r5.log 2>&1 \
          && echo "[watch] parity captured" >&2
      fi
      if [ ! -e BENCH_SELF_r05_int8.json ]; then
        echo "[watch] -> int8 bench" >&2
        rm -f .bench_state.json
        # per-attempt truncated, PID-unique paths: the published .log must
        # contain exactly the run that produced the .json next to it
        qj=/tmp/bench_q_$$.json ql=/tmp/bench_q_$$.log
        BENCH_QUANT=int8 BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$qj" 2>"$ql"
        qvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['value'])" \
            "$qj" 2>/dev/null || echo 0)
        case "$qvalue" in
          0|0.0|"") echo "[watch] int8 got no number" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$qj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r05_int8.json", "w"), indent=1)
EOF
            cp "$ql" BENCH_SELF_r05_int8.log 2>/dev/null
            echo "[watch] int8 captured: $qvalue" >&2 ;;
        esac
      fi
      if [ ! -e BENCH_SELF_r05_w128.json ] \
          && [ -e BENCH_SELF_r05_int8.json ]; then
        # decode_steps=128 experiment: r3 pinned 64 as the knee BEFORE
        # split-KV decoupled the base attention read from the allocation
        # width; re-measure the window-size scaling on the new geometry
        echo "[watch] -> decode_steps=128 bench" >&2
        rm -f .bench_state.json
        wj=/tmp/bench_w_$$.json wl=/tmp/bench_w_$$.log
        BENCH_DECODE_STEPS=128 BENCH_BUDGET_S=1200 timeout 1500 \
            python bench.py >"$wj" 2>"$wl"
        wvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['value'])" \
            "$wj" 2>/dev/null || echo 0)
        case "$wvalue" in
          0|0.0|"") echo "[watch] w128 got no number" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$wj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
r["decode_steps"] = 128
json.dump(r, open("BENCH_SELF_r05_w128.json", "w"), indent=1)
EOF
            cp "$wl" BENCH_SELF_r05_w128.log 2>/dev/null
            echo "[watch] w128 captured: $wvalue" >&2 ;;
        esac
      fi
      if [ ! -e PARITY_TPU_r06_int8.json ]; then
        # int8 evidence set completion (VERDICT weak #6): the r05 int8
        # capture has a bench number but no parity run — the int8
        # matmul path needs its own window-vs-single-step token check
        echo "[watch] -> int8 parity" >&2
        BENCH_QUANT=int8 PARITY_OUT=PARITY_TPU_r06_int8.json \
          timeout 900 python tools/tpu_parity_quick.py \
          >> tpu_parity_r6_int8.log 2>&1 \
          && echo "[watch] int8 parity captured" >&2
      fi
      if [ ! -e BENCH_SELF_r06_int8_churn.json ] \
          && [ -e BENCH_SELF_r05_int8.json ]; then
        # int8 churn capture: BENCH_SELF_r05_int8 predates the churn
        # phase's ITL/stall instrumentation AND the mixed-step scheduler;
        # this run records churn_mixed vs churn_alternating (ITL p50/95/
        # 99 + decode_stall_steps) on the int8 engine in one run
        echo "[watch] -> int8 churn bench" >&2
        rm -f .bench_state.json
        cj=/tmp/bench_c_$$.json cl=/tmp/bench_c_$$.log
        BENCH_QUANT=int8 BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$cj" 2>"$cl"
        cvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('agg_churn_tok_s',0))" \
            "$cj" 2>/dev/null || echo 0)
        case "$cvalue" in
          0|0.0|"") echo "[watch] int8 churn got no number" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$cj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r06_int8_churn.json", "w"), indent=1)
EOF
            cp "$cl" BENCH_SELF_r06_int8_churn.log 2>/dev/null
            echo "[watch] int8 churn captured: $cvalue" >&2 ;;
        esac
      fi
      if [ ! -e PARITY_TPU_r06_kvq.json ]; then
        # kv-cache int8 parity gate (ROADMAP item 5): the SAME
        # bench.run_kv_quant_parity thresholds the tier-1 CPU gate
        # enforces (greedy-match >= 0.99 + bounded logit drift), on
        # hardware — the one check Mosaic/bf16 numerics could move
        echo "[watch] -> kv_quant parity" >&2
        PARITY_KV_QUANT=int8 PARITY_OUT=PARITY_TPU_r06_kvq.json \
          timeout 900 python tools/tpu_parity_quick.py \
          >> tpu_parity_r6_kvq.log 2>&1 \
          && echo "[watch] kv_quant parity captured" >&2
      fi
      if [ ! -e BENCH_SELF_r06_kvq.json ] \
          && [ -e BENCH_SELF_r05_int8.json ]; then
        # kv_quant A/B capture: extras.kv_quant (capacity at fixed HBM
        # page budget + int8-KV churn) from the bench's kv_quant_ab
        # phase, on an int8-WEIGHT engine so both HBM levers compose
        echo "[watch] -> kv_quant bench" >&2
        rm -f .bench_state.json
        kj=/tmp/bench_k_$$.json kl=/tmp/bench_k_$$.log
        BENCH_QUANT=int8 BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$kj" 2>"$kl"
        kvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('kv_quant',{}).get('churn_int8_tok_s',0))" \
            "$kj" 2>/dev/null || echo 0)
        case "$kvalue" in
          0|0.0|"") echo "[watch] kv_quant bench got no number" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$kj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r06_kvq.json", "w"), indent=1)
EOF
            cp "$kl" BENCH_SELF_r06_kvq.log 2>/dev/null
            echo "[watch] kv_quant bench captured: $kvalue" >&2 ;;
        esac
      fi
      if [ ! -e BENCH_SELF_r11_overlap_tpu.json ]; then
        # disagg TTFT overlap A/B on hardware (ISSUE 11): the bench's
        # transfer_overlap phase (agg vs disagg-wait vs disagg-early
        # TTFT + routing A/B) on the flagship, and — via the supervisor's
        # ratio trajectory rows — a real row for the
        # disagg_decode_gain_llama3_1b_tpu / disagg_agg_ttft_ratio
        # gates in BASELINE.json (tools/bench_compare.py scores it)
        echo "[watch] -> transfer-overlap bench" >&2
        rm -f .bench_state.json
        oj=/tmp/bench_o_$$.json ol=/tmp/bench_o_$$.log
        BENCH_RUN_ID=BENCH_SELF_r11_overlap_tpu BENCH_KVQ=0 \
          BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$oj" 2>"$ol"
        ovalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('transfer_overlap',{}).get('disagg_agg_ttft_ratio_early',0))" \
            "$oj" 2>/dev/null || echo 0)
        case "$ovalue" in
          0|0.0|"") echo "[watch] transfer-overlap bench got no ratio" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$oj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r11_overlap_tpu.json", "w"), indent=1)
EOF
            cp "$ol" BENCH_SELF_r11_overlap_tpu.log 2>/dev/null
            echo "[watch] transfer-overlap captured: ratio $ovalue" >&2 ;;
        esac
      fi
      if [ ! -e BENCH_SELF_r13_warm_prefix_tpu.json ]; then
        # warm-prefix shared-pool ladder on hardware (ISSUE 13): cold vs
        # local-hit vs pool-fetch vs pool-prefetch TTFT on the flagship
        # — via the supervisor's ratio trajectory rows this is also the
        # measured row for the pre-registered
        # warm_prefix_pool_fetch_ttft_ratio_llama3_1b_tpu gate in
        # BASELINE.json (tools/bench_compare.py scores it), AND the
        # overdue real-TPU headline row the ROADMAP re-anchor asks every
        # TPU window to recapture through the bench_compare gate
        echo "[watch] -> warm-prefix pool bench" >&2
        rm -f .bench_state.json
        wj=/tmp/bench_w_$$.json wl=/tmp/bench_w_$$.log
        BENCH_RUN_ID=BENCH_SELF_r13_warm_prefix_tpu BENCH_KVQ=0 \
          BENCH_OVERLAP=0 BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$wj" 2>"$wl"
        wvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('warm_prefix',{}).get('pool_fetch_cold_ttft_ratio',0))" \
            "$wj" 2>/dev/null || echo 0)
        case "$wvalue" in
          0|0.0|"") echo "[watch] warm-prefix bench got no ratio" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$wj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r13_warm_prefix_tpu.json", "w"), indent=1)
EOF
            cp "$wl" BENCH_SELF_r13_warm_prefix_tpu.log 2>/dev/null
            echo "[watch] warm-prefix captured: fetch/cold $wvalue" >&2 ;;
        esac
      fi
      if [ ! -e BENCH_SELF_r15_sharded_tpu.json ]; then
        # sharded parallel KV transfer on hardware (ISSUE 15): 1-stream
        # vs N-(shard, host)-stream transfer wall time + disagg TTFT on
        # the flagship — via the supervisor's ratio trajectory rows this
        # is the measured row for the pre-registered
        # sharded_transfer_wall_ratio_llama3_1b_tpu gate in BASELINE.json
        # (tools/bench_compare.py scores it), AND another recapture of
        # the overdue real-TPU headline row the ROADMAP re-anchor asks
        # every TPU window to take through the bench_compare gate
        echo "[watch] -> sharded-transfer bench" >&2
        rm -f .bench_state.json
        hj=/tmp/bench_h_$$.json hl=/tmp/bench_h_$$.log
        BENCH_RUN_ID=BENCH_SELF_r15_sharded_tpu BENCH_KVQ=0 \
          BENCH_OVERLAP=0 BENCH_WARM_PREFIX=0 BENCH_BUDGET_S=1200 \
          timeout 1500 python bench.py >"$hj" 2>"$hl"
        hvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('sharded_transfer',{}).get('paced_wall_ratio',0))" \
            "$hj" 2>/dev/null || echo 0)
        case "$hvalue" in
          0|0.0|"") echo "[watch] sharded-transfer bench got no ratio" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$hj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r15_sharded_tpu.json", "w"), indent=1)
EOF
            cp "$hl" BENCH_SELF_r15_sharded_tpu.log 2>/dev/null
            echo "[watch] sharded transfer captured: wall ratio $hvalue" >&2 ;;
        esac
      fi
      if [ ! -e BENCH_SELF_r17_pool_remote_tpu.json ]; then
        # remote-pool rungs on hardware (ISSUE 17): the warm-prefix
        # ladder's remote_fetch / remote_prefetch TTFT through the
        # served ClusterKvPool (hash-ring placement, R=2, per-page
        # checksum verify on the serving host) on the flagship — via
        # the supervisor's ratio trajectory rows this is the measured
        # row for the pre-registered
        # warm_prefix_remote_fetch_ttft_ratio_llama3_1b_tpu gate in
        # BASELINE.json (tools/bench_compare.py scores it)
        echo "[watch] -> remote-pool bench" >&2
        rm -f .bench_state.json
        rj=/tmp/bench_r_$$.json rl=/tmp/bench_r_$$.log
        BENCH_RUN_ID=BENCH_SELF_r17_pool_remote_tpu BENCH_KVQ=0 \
          BENCH_OVERLAP=0 BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$rj" 2>"$rl"
        rvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('warm_prefix',{}).get('remote_fetch_cold_ttft_ratio',0))" \
            "$rj" 2>/dev/null || echo 0)
        case "$rvalue" in
          0|0.0|"") echo "[watch] remote-pool bench got no ratio" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$rj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r17_pool_remote_tpu.json", "w"), indent=1)
EOF
            cp "$rl" BENCH_SELF_r17_pool_remote_tpu.log 2>/dev/null
            echo "[watch] remote-pool captured: remote-fetch/cold $rvalue" >&2 ;;
        esac
      fi
      if [ ! -e PARITY_TPU_r18_ragged.json ]; then
        # ragged-kernel parity on hardware (ISSUE 18): window-vs-single-
        # step greedy token check with decode_kernel=on, so the unified
        # Pallas kernel (not the serving-default gather) carries the
        # forward pass — Mosaic numerics are the one thing the CPU
        # interpret-mode parity matrix (tests/test_ragged_kernel.py)
        # cannot exercise
        echo "[watch] -> ragged-kernel parity" >&2
        PARITY_DECODE_KERNEL=on PARITY_OUT=PARITY_TPU_r18_ragged.json \
          timeout 900 python tools/tpu_parity_quick.py \
          >> tpu_parity_r18_ragged.log 2>&1 \
          && echo "[watch] ragged-kernel parity captured" >&2
      fi
      if [ ! -e BENCH_SELF_r18_ragged_tpu.json ]; then
        # ragged-kernel + fused-tail A/B on hardware (ISSUE 18): the
        # bench's decode_kernel_ab phase (frozen legacy trio vs unified
        # ragged kernel vs unified+fused sampling tail, token-identity
        # asserted in-phase) on the flagship's geometry — via the
        # supervisor's ratio trajectory rows this is the measured row for
        # the pre-registered
        # decode_kernel_unified_legacy_step_ratio_llama3_1b_tpu gate in
        # BASELINE.json (tools/bench_compare.py scores it), AND another
        # recapture of the overdue real-TPU headline row (last measured:
        # BENCH_r02's 81.33 tok/s/chip) the ROADMAP re-anchor asks every
        # TPU window to take through the bench_compare gate
        echo "[watch] -> ragged-kernel bench" >&2
        rm -f .bench_state.json
        gj=/tmp/bench_g_$$.json gl=/tmp/bench_g_$$.log
        BENCH_RUN_ID=BENCH_SELF_r18_ragged_tpu BENCH_KVQ=0 \
          BENCH_OVERLAP=0 BENCH_WARM_PREFIX=0 BENCH_SHARDED=0 \
          BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$gj" 2>"$gl"
        gvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('decode_kernel',{}).get('unified_legacy_step_ratio',0))" \
            "$gj" 2>/dev/null || echo 0)
        case "$gvalue" in
          0|0.0|"") echo "[watch] ragged-kernel bench got no ratio" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$gj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r18_ragged_tpu.json", "w"), indent=1)
EOF
            cp "$gl" BENCH_SELF_r18_ragged_tpu.log 2>/dev/null
            echo "[watch] ragged kernel captured: unified/legacy $gvalue" >&2 ;;
        esac
      fi
      if [ ! -e BENCH_SELF_r19_failslow_tpu.json ]; then
        # fail-slow plane on hardware (ISSUE 19): the hedged-dispatch
        # token-identity contracts (greedy + seeded-sampled, aggregated
        # + disagg) against the REAL engine — the CPU tier-1 runs prove
        # the race discipline, but only a hardware pass proves a hedge
        # race stays token-identical under Mosaic numerics — then the
        # fail_slow_storm A/B replay for the recorded p99 margin and
        # its four contracts (margin, zero drops, zero false ejections,
        # bit-identical decision timeline)
        echo "[watch] -> fail-slow hedging evidence" >&2
        fl=/tmp/failslow_$$.log fj=/tmp/failslow_$$.json
        if timeout 900 python -m pytest tests/test_chaos.py -q \
              -k "hedge" -p no:cacheprovider >"$fl" 2>&1 \
            && timeout 600 python tools/chaos_replay.py fail_slow_storm \
              >"$fj" 2>>"$fl"; then
          python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$fj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
r["run_id"] = "BENCH_SELF_r19_failslow_tpu"
json.dump(r, open("BENCH_SELF_r19_failslow_tpu.json", "w"), indent=1)
EOF
          cp "$fl" BENCH_SELF_r19_failslow_tpu.log 2>/dev/null
          echo "[watch] fail-slow evidence captured" >&2
        else
          echo "[watch] fail-slow hedging run failed (log: $fl)" >&2
        fi
      fi
      if [ ! -e BENCH_SELF_r20_long_context_tpu.json ]; then
        # tiered-KV streaming decode on hardware (ISSUE 20): the bench's
        # long_context phase — a streamed engine whose HBM page budget is
        # 1/4 of the context vs an oversized-HBM resident oracle, token
        # identity asserted per rung, per-token ITL percentiles on both,
        # prefetch hit/late/spill counters from STREAM_STATS — on the
        # flagship's geometry — via the supervisor's ratio trajectory
        # rows this is the measured row for the pre-registered
        # long_context_itl_inflation_4x_llama3_1b_tpu gate in
        # BASELINE.json (tools/bench_compare.py scores it), AND another
        # recapture of the overdue real-TPU headline row (last measured:
        # BENCH_r02's 81.33 tok/s/chip) the ROADMAP re-anchor asks every
        # TPU window to take through the bench_compare gate
        echo "[watch] -> long-context streaming bench" >&2
        rm -f .bench_state.json
        lj=/tmp/bench_l_$$.json ll=/tmp/bench_l_$$.log
        BENCH_RUN_ID=BENCH_SELF_r20_long_context_tpu BENCH_KVQ=0 \
          BENCH_OVERLAP=0 BENCH_WARM_PREFIX=0 BENCH_SHARDED=0 \
          BENCH_DECODE_KERNEL=0 BENCH_BUDGET_S=1200 timeout 1500 \
          python bench.py >"$lj" 2>"$ll"
        lvalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('long_context',{}).get('itl_inflation_4x',0))" \
            "$lj" 2>/dev/null || echo 0)
        case "$lvalue" in
          0|0.0|"") echo "[watch] long-context bench got no ratio" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$lj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r20_long_context_tpu.json", "w"), indent=1)
EOF
            cp "$ll" BENCH_SELF_r20_long_context_tpu.log 2>/dev/null
            echo "[watch] long-context captured: streamed/resident ITL $lvalue" >&2 ;;
        esac
      fi
      if [ ! -e BENCH_SELF_r05_spec.json ] \
          && [ -e BENCH_SELF_r05_int8.json ]; then
        # speculative-decoding ceiling: oracle drafts at acceptance ~1.0
        # measure the verify path's full-acceptance throughput (extras
        # spec_ceiling_tok_s / spec_speedup) on hardware
        echo "[watch] -> spec-ceiling bench" >&2
        rm -f .bench_state.json
        sj=/tmp/bench_s_$$.json sl=/tmp/bench_s_$$.log
        BENCH_SPEC=oracle BENCH_BUDGET_S=1200 timeout 1500 python bench.py \
            >"$sj" 2>"$sl"
        svalue=$(python -c "import json,sys;print(json.load(open(sys.argv[1]))['extras'].get('spec_ceiling_tok_s',0))" \
            "$sj" 2>/dev/null || echo 0)
        case "$svalue" in
          0|0.0|"") echo "[watch] spec ceiling got no number" >&2 ;;
          *)
            python - "$(date -u +%Y-%m-%dT%H:%M:%SZ)" "$sj" <<'EOF'
import json, sys
r = json.load(open(sys.argv[2]))
r["timestamp"] = sys.argv[1]
r["self_measured"] = True
json.dump(r, open("BENCH_SELF_r05_spec.json", "w"), indent=1)
EOF
            cp "$sl" BENCH_SELF_r05_spec.log 2>/dev/null
            echo "[watch] spec ceiling captured: $svalue" >&2 ;;
        esac
      fi
      # LAST: the longest item (checkpoint build + serve + oracle) —
      # ordered after the bench numbers so a short up-window is not
      # consumed before the perf evidence lands (the 07:19 window was)
      if [ ! -e real_ckpt_e2e_tpu.log ]; then
        # 1800s: the e2e now serves TWICE (base + --spec-decode), each
        # with its own engine build/compiles (code-review r5)
        echo "[watch] -> real-checkpoint e2e on TPU" >&2
        timeout 1800 python tools/real_ckpt_e2e.py --out real_ckpt_e2e_tpu.log \
          >> tpu_realckpt_r5.log 2>&1 \
          && echo "[watch] real-ckpt TPU captured" >&2 \
          || rm -f real_ckpt_e2e_tpu.log   # partial/failed run: retry next window
      fi ;;
    *) : ;;  # down; loop
  esac
  sleep 45
done
