#!/usr/bin/env python
"""routing_ab: the transfer-aware-routing A/B evidence driver.

Stands up the simulated cluster (runtime/simcluster.py), assigns every
worker link a SEEDED wire bandwidth from a two-decade tier ladder plus
a per-link seeded delay-fault schedule (the `transfer.link` stall
model), then replays the identical seeded request stream through
prefix-overlap-only scoring and through transfer-aware scoring
(kv_router TransferAwareSelector over a TransferCostModel that learns
only from the simulation's own completed transfers). Commits the
report via tools/artifacts.py — the same seed regenerates the same
artifact bit-for-bit (pinned by tests/test_cluster_sim.py).

Usage:
    python tools/routing_ab.py --workers 1000 --requests 4000 \
        --seed 11 --out ROUTING_AB_r11.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


async def run(args) -> dict:
    from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig
    sim = await SimCluster(SimConfig(
        workers=args.workers, streams=args.streams, seed=args.seed)).start()
    try:
        report = await sim.routing_ab(requests=args.requests)
    finally:
        await sim.stop()
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="routing_ab", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--workers", type=int, default=1000)
    ap.add_argument("--streams", type=int, default=4096)
    ap.add_argument("--requests", type=int, default=4000)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--out", default=None,
                    help="commit the report as an evidence artifact "
                         "(tools/artifacts.py policy); default: stdout only")
    ap.add_argument("--overwrite", action="store_true")
    args = ap.parse_args(argv)
    report = asyncio.run(run(args))
    print(json.dumps(report, indent=1))
    ok = report["transfer_aware"]["ttft_p99_ms"] \
        < report["prefix_only"]["ttft_p99_ms"]
    print(f"p99 TTFT: prefix-only {report['prefix_only']['ttft_p99_ms']}ms"
          f" -> transfer-aware {report['transfer_aware']['ttft_p99_ms']}ms"
          f" ({report['p99_improvement'] * 100:.1f}% better)"
          if ok else "NO p99 improvement", file=sys.stderr)
    if args.out:
        from tools.artifacts import write_json
        write_json(args.out, report, overwrite=args.overwrite)
        print(f"-> {args.out}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
