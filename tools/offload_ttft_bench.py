"""Host-tier KV offload TTFT evidence (BASELINE.md "KV cache offload to
CPU RAM: TTFT +40% over prefix-caching alone").

The reference's claim (reference: docs/architecture.md:91-95 — 10
multi-turn conversations x 80 users, KV offloaded to CPU RAM restored
instead of recomputed) rests on one mechanism: when HBM page pressure
evicts a conversation's prefix KV, a host DRAM tier lets the next turn
RESTORE those pages (a DMA upload) instead of recomputing prefill. This
bench drives that mechanism through OUR full stack (same harness as
tools/routing_ttft_bench.py — real control plane, one real worker via
`dynamo_tpu.run in=endpoint out=native`, real HTTP frontend):

  A) --host-pages > 0 (engine/offload.py DRAM tier on), vs
  B) --host-pages 0 (prefix caching alone: evicted pages are simply gone)

Workload: C conversations x fixed prefix, interleaved turns, with
num_pages sized so ALL conversations cannot fit in HBM at once — every
revisit finds its prefix evicted. With the tier on, revisit TTFT pays a
host->HBM page upload; with it off, a full recompute. Emits
OFFLOAD_TTFT.json: revisit-turn TTFT per mode + the improvement ratio.

Scale note: on CPU the "DMA upload" and the recompute both run on the
host so the gap is mechanism-bound, not bandwidth-bound; on a TPU
backend the same script runs unchanged and the gap widens (upload rides
PCIe/DMA, recompute burns MXU prefill).

Run: python tools/offload_ttft_bench.py [--conversations 6 --turns 3]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import statistics
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from routing_ttft_bench import Stack, log  # noqa: E402


def run_mode(host_pages: int, args, workdir: str) -> dict:
    tag = f"tier{host_pages}" if host_pages else "no-tier"
    # num_pages: fit ~half the conversations' prefixes at once, so
    # interleaved turns evict each other's prefixes every round
    pages_per_conv = -(-args.prefix_tokens // 64) + 2
    num_pages = max(16, pages_per_conv * (args.conversations // 2))
    stack = Stack(1, kv_routed=False, tag=tag,
                  worker_args=["--num-pages", str(num_pages),
                               "--host-pages", str(host_pages)])
    rng = random.Random(4321)  # same workload both modes
    convs = [[rng.randrange(1, 1000) for _ in range(args.prefix_tokens)]
             for _ in range(args.conversations)]
    sufs = [[[rng.randrange(1, 1000) for _ in range(16)]
             for _ in range(args.turns)] for _ in range(args.conversations)]
    try:
        stack.start(os.path.join(workdir, tag))
        log(f"[{tag}] stack up (num_pages={num_pages}, "
            f"host_pages={host_pages})")

        def epoch(conversations, suffixes, record):
            per_turn = []
            for t in range(args.turns):
                ttfts = []
                for c in range(len(conversations)):
                    prompt = list(conversations[c])
                    for u in range(t + 1):
                        prompt += suffixes[c][u]
                    ttft, _ = stack.request_ttft(prompt,
                                                 max_tokens=args.max_tokens)
                    ttfts.append(ttft)
                per_turn.append(ttfts)
                if record:
                    log(f"[{tag}] turn {t}: p50 "
                        f"{statistics.median(ttfts)*1e3:.0f} ms")
            return per_turn

        # warm epoch: the SAME workload shape with throwaway conversations
        # — same pool pressure, so the eviction + (tier-on) offload/restore
        # paths and every XLA program variant compile here, not inside a
        # timed revisit (same rationale as routing_ttft_bench's warmup)
        wrng = random.Random(999)
        wconvs = [[wrng.randrange(1, 1000) for _ in range(args.prefix_tokens)]
                  for _ in range(args.conversations)]
        wsufs = [[[wrng.randrange(1, 1000) for _ in range(16)]
                  for _ in range(args.turns)]
                 for _ in range(args.conversations)]
        epoch(wconvs, wsufs, record=False)
        log(f"[{tag}] warm epoch done")
        per_turn = epoch(convs, sufs, record=True)
        revisit = [x for turn in per_turn[1:] for x in turn]
        return {
            "mode": tag, "num_pages": num_pages, "host_pages": host_pages,
            "revisit_ttft_p50_ms": round(statistics.median(revisit) * 1e3, 1),
            "revisit_ttft_mean_ms": round(statistics.fmean(revisit) * 1e3, 1),
            "per_turn_p50_ms": [round(statistics.median(t) * 1e3, 1)
                                for t in per_turn],
            "raw_ttft_ms": [[round(x * 1e3, 1) for x in t]
                            for t in per_turn],
        }
    finally:
        stack.stop()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--conversations", type=int, default=6)
    ap.add_argument("--turns", type=int, default=3)
    ap.add_argument("--prefix-tokens", type=int, default=768)
    ap.add_argument("--max-tokens", type=int, default=8)
    ap.add_argument("--host-pages", type=int, default=256)
    ap.add_argument("--out", default=os.path.join(HERE, "OFFLOAD_TTFT.json"))
    args = ap.parse_args()

    import tempfile
    with tempfile.TemporaryDirectory() as workdir:
        off = run_mode(0, args, workdir)
        on = run_mode(args.host_pages, args, workdir)

    result = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "workload": {"conversations": args.conversations,
                     "turns": args.turns,
                     "prefix_tokens": args.prefix_tokens,
                     "max_tokens": args.max_tokens, "model": "tiny",
                     "workers": 1},
        "prefix_cache_only": off, "host_tier": on,
        "ttft_improvement": round(
            off["revisit_ttft_p50_ms"] / on["revisit_ttft_p50_ms"], 2)
        if on["revisit_ttft_p50_ms"] else None,
    }
    from tools.artifacts import write_json
    write_json(args.out, result, overwrite=True)  # final name, no renames
    log("wrote", args.out)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
