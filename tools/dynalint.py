#!/usr/bin/env python
"""dynalint CLI: project-specific static analysis + jaxpr invariant audit.

Usage:
    python tools/dynalint.py [paths...]          # lint + jaxpr audit
    python tools/dynalint.py --no-jaxpr          # AST layer only
    python tools/dynalint.py --write-baseline    # regenerate the baseline
    python tools/dynalint.py --no-baseline       # show ALL findings

Exit code 0 when every finding is covered by tools/dynalint_baseline.json
(or inline `# dynalint: disable=Rn` annotations), 1 otherwise — so the
command itself is CI-gateable; tests/test_dynalint.py runs the same
entry points under the tier-1 pytest gate. See docs/ANALYSIS.md.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools",
                                "dynalint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dynalint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO_ROOT, "dynamo_tpu")],
                    help="files/directories to lint (default: dynamo_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/"
                         "dynalint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the layer-2 jaxpr audit (pure AST lint; "
                         "no jax import)")
    args = ap.parse_args(argv)

    from dynamo_tpu.analysis import (
        filter_baseline, load_baseline, run_lint, save_baseline,
    )

    findings = run_lint(args.paths, root=REPO_ROOT)
    if not args.no_jaxpr:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from dynamo_tpu.analysis import audit_engine_entry_points
        findings += audit_engine_entry_points()

    if args.write_baseline:
        save_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = None if args.no_baseline else load_baseline(args.baseline)
    fresh = filter_baseline(findings, baseline)
    for f in fresh:
        print(f.render())
    suppressed = len(findings) - len(fresh)
    tag = f" ({suppressed} baselined)" if suppressed else ""
    print(f"dynalint: {len(fresh)} new finding(s){tag}")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
