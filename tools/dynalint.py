#!/usr/bin/env python
"""dynalint CLI: project-specific static analysis + jaxpr invariant audit.

Usage:
    python tools/dynalint.py [paths...]          # lint + jaxpr audit
    python tools/dynalint.py --no-jaxpr          # AST layer only
    python tools/dynalint.py --write-baseline    # regenerate the baseline
    python tools/dynalint.py --no-baseline       # show ALL findings
    python tools/dynalint.py --changed           # only files changed vs
                                                 # the merge-base (implies
                                                 # --no-jaxpr)
    python tools/dynalint.py --json              # machine-readable output

Exit code 0 when every finding is covered by tools/dynalint_baseline.json
(or inline `# dynalint: disable=Rn` annotations), 1 otherwise — so the
command itself is CI-gateable; tests/test_dynalint.py runs the same
entry points under the tier-1 pytest gate. See docs/ANALYSIS.md.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools",
                                "dynalint_baseline.json")


def changed_py_files(root: str = REPO_ROOT):
    """Python files changed vs the merge-base with the main branch, plus
    untracked ones — the pre-push fast path. Returns repo-relative
    forward-slash paths; raises RuntimeError when git is unusable."""
    import subprocess

    def git(*cmd):
        return subprocess.run(
            ("git",) + cmd, cwd=root, capture_output=True, text=True,
            timeout=30)

    base = None
    for ref in ("origin/main", "origin/master", "main", "master"):
        r = git("merge-base", "HEAD", ref)
        if r.returncode == 0:
            base = r.stdout.strip()
            break
    if base is None:
        # detached/shallow fallback: everything in the working tree vs HEAD
        base = "HEAD"
    diff = git("diff", "--name-only", base, "--")
    if diff.returncode != 0:
        raise RuntimeError(f"git diff failed: {diff.stderr.strip()}")
    untracked = git("ls-files", "--others", "--exclude-standard")
    names = set(diff.stdout.splitlines())
    if untracked.returncode == 0:
        names |= set(untracked.stdout.splitlines())
    return sorted(
        n.replace("\\", "/") for n in names
        if n.endswith(".py") and os.path.exists(os.path.join(root, n)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dynalint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO_ROOT, "dynamo_tpu")],
                    help="files/directories to lint (default: dynamo_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/"
                         "dynalint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report every finding")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline "
                         "and exit 0")
    ap.add_argument("--no-jaxpr", action="store_true",
                    help="skip the layer-2 jaxpr audit (pure AST lint; "
                         "no jax import)")
    ap.add_argument("--changed", action="store_true",
                    help="lint only .py files changed vs the merge-base "
                         "with main (plus untracked); implies --no-jaxpr")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON on stdout (exit code "
                         "semantics unchanged)")
    args = ap.parse_args(argv)

    from dynamo_tpu.analysis import (
        filter_baseline, load_baseline, run_lint, save_baseline,
    )

    paths = args.paths
    if args.changed:
        # diff-scoped fast path: whole-program jaxpr audit makes no sense
        # against a file subset, so the layer-2 pass is skipped
        args.no_jaxpr = True
        names = changed_py_files()
        paths = [os.path.join(REPO_ROOT, n) for n in names]
        if not paths:
            if args.as_json:
                print(json.dumps({"findings": [], "fresh": 0,
                                  "baselined": 0, "files": []}))
            else:
                print("dynalint: no changed python files")
            return 0

    findings = run_lint(paths, root=REPO_ROOT)
    if not args.no_jaxpr:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from dynamo_tpu.analysis import audit_engine_entry_points
        findings += audit_engine_entry_points()

    if args.write_baseline:
        save_baseline(args.baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.baseline}")
        return 0

    baseline = None if args.no_baseline else load_baseline(args.baseline)
    fresh = filter_baseline(findings, baseline)
    if args.as_json:
        payload = {
            "findings": [dataclasses.asdict(f) for f in fresh],
            "fresh": len(fresh),
            "baselined": len(findings) - len(fresh),
        }
        if args.changed:
            payload["files"] = [os.path.relpath(p, REPO_ROOT)
                                .replace("\\", "/") for p in paths]
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 1 if fresh else 0
    for f in fresh:
        print(f.render())
    suppressed = len(findings) - len(fresh)
    tag = f" ({suppressed} baselined)" if suppressed else ""
    print(f"dynalint: {len(fresh)} new finding(s){tag}")
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())
