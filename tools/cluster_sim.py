#!/usr/bin/env python
"""cluster_sim: the control-plane scale harness (ROADMAP item 4).

Stands up a simulated cluster (dynamo_tpu/runtime/simcluster.py) of mock
workers — instance keys + leases + $STATS responders + synthetic
KV-event streams, no model — against a REAL Client + KvRouter, then:

1. runs a capacity ladder (workers vs. schedule p50/p99, per-scrape
   aggregation cost, registration time);
2. probes the event plane (publish rate vs. applied rate, peak backlog
   and lag);
3. drives seeded chaos storms at full scale: a rolling restart of a
   fleet fraction under load, a lease-expiry burst, a watch-disconnect
   storm (watch.stream failpoint), and an event-plane lag storm that
   must round-trip the router's stale-snapshot degraded mode;
4. commits the capacity curves + storm contracts as a single evidence
   artifact via tools/artifacts.py (append-forbidden single JSON,
   final name — default SCALE_r07.json).

Contracts enforced (exit 1 on violation):
- zero scheduling errors across every phase;
- zero post-fence picks (the router never selects a dead/draining
  worker after its watch event is applied);
- the watch-disconnect storm converges (resumed watcher resyncs);
- the lag storm enters AND exits degraded mode.

Usage:
    python tools/cluster_sim.py --workers 1000 --streams 20000 --seed 7
    python tools/cluster_sim.py --workers 64 --quick      # smoke shape
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from dynamo_tpu.runtime.simcluster import SimCluster, SimConfig  # noqa: E402


async def run_point(workers: int, streams: int, seed: int,
                    load_calls: int) -> dict:
    """One capacity-ladder point: fleet up, load, scrape cost, down."""
    sim = await SimCluster(SimConfig(
        workers=workers, streams=streams, seed=seed)).start()
    try:
        load = await sim.run_load(load_calls)
        scrape_s = await sim.measure_scrape()
        return {"workers": workers, "register_s": round(sim.register_s, 3),
                "scrape_ms": round(scrape_s * 1e3, 2), **load,
                "indexer_nodes": sim.router.indexer.num_nodes(),
                "errors": sim.schedule_errors,
                "dead_picks": sim.dead_picks}
    finally:
        await sim.stop()


async def run_full(args) -> dict:
    t_start = time.time()
    ladder = sorted({min(64, args.workers), min(256, args.workers),
                     args.workers})
    report = {"seed": args.seed, "workers": args.workers,
              "streams": args.streams, "started_unix": round(t_start, 3)}

    # 1. capacity ladder
    curve = []
    for n in ladder:
        point = await run_point(n, min(args.streams, n * 32), args.seed,
                                args.load_calls)
        print(f"ladder {n:>5} workers: {json.dumps(point)}", flush=True)
        curve.append(point)
    report["workers_vs_latency"] = curve

    # 2..4 run on one full-scale cluster
    sim = await SimCluster(SimConfig(
        workers=args.workers, streams=args.streams, seed=args.seed)).start()
    try:
        probe = await sim.event_rate_probe(events=args.probe_events)
        print(f"event probe: {json.dumps(probe)}", flush=True)
        report["events_vs_lag"] = probe

        storms = {}
        storms["rolling_restart"] = await sim.storm_rolling_restart(
            fraction=args.restart_fraction, load_calls=args.load_calls)
        print(f"rolling restart: {json.dumps(storms['rolling_restart'])}",
              flush=True)
        storms["lease_expiry"] = await sim.storm_lease_expiry(
            fraction=0.1, load_calls=args.load_calls // 2)
        print(f"lease expiry: {json.dumps(storms['lease_expiry'])}",
              flush=True)
        storms["watch_disconnect"] = await sim.storm_watch_disconnect(
            kills=3, load_calls=args.load_calls // 4)
        print(f"watch disconnect: {json.dumps(storms['watch_disconnect'])}",
              flush=True)
        storms["event_lag"] = await sim.storm_event_lag(
            delay_s=1.5, load_calls=args.load_calls // 4)
        print(f"event lag: {json.dumps(storms['event_lag'])}", flush=True)
        report["storms"] = storms
        report["summary"] = sim.summary()
    finally:
        await sim.stop()

    report["elapsed_s"] = round(time.time() - t_start, 1)
    report["contracts"] = {
        "zero_schedule_errors": report["summary"]["schedule_errors"] == 0
        and all(p["errors"] == 0 for p in curve),
        "zero_dead_picks": report["summary"]["dead_picks"] == 0
        and all(p["dead_picks"] == 0 for p in curve),
        "watch_converged": storms["watch_disconnect"]["converged"],
        "degraded_round_trip": storms["event_lag"]["entered"]
        and storms["event_lag"]["exited"],
    }
    report["ok"] = all(report["contracts"].values())
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="cluster_sim", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--workers", type=int, default=1000)
    ap.add_argument("--streams", type=int, default=20_000)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--load-calls", type=int, default=4000,
                    help="schedule decisions per load phase")
    ap.add_argument("--probe-events", type=int, default=8000)
    ap.add_argument("--restart-fraction", type=float, default=0.3,
                    help="fleet fraction cycled by the rolling restart")
    ap.add_argument("--quick", action="store_true",
                    help="shrink loads for a fast shape check")
    ap.add_argument("--out", default=os.path.join(REPO_ROOT,
                                                  "SCALE_r07.json"),
                    help="evidence artifact path (tools/artifacts.py "
                         "policy: final name, no clobber)")
    ap.add_argument("--no-artifact", action="store_true")
    ap.add_argument("--trace", metavar="TRACE_JSONL", nargs="?",
                    const=os.path.join(REPO_ROOT, "SCALE_TRACE.jsonl"),
                    help="capture router.schedule spans during the storms "
                         "(sample=1.0) and append them to this JSONL plus "
                         "a chrome://tracing twin at <path>.chrome.json, "
                         "via tools/artifacts.py")
    args = ap.parse_args(argv)
    if args.quick:
        args.load_calls = min(args.load_calls, 500)
        args.probe_events = min(args.probe_events, 1000)
    if args.trace:
        from dynamo_tpu.runtime.tracing import TRACER
        TRACER.configure(enabled=True, sample_rate=1.0)
        TRACER.drain()  # start the capture clean

    report = asyncio.run(run_full(args))
    if args.trace:
        from dynamo_tpu.runtime.tracing import TRACER, chrome_trace

        from tools.artifacts import append_jsonl, write_json
        spans = TRACER.drain()
        for span in spans:
            append_jsonl(args.trace, span)
        write_json(args.trace + ".chrome.json", chrome_trace(spans),
                   overwrite=True)
        report["trace_spans"] = len(spans)
        report["trace_file"] = args.trace
        print(f"captured {len(spans)} span(s) -> {args.trace} "
              f"(+ .chrome.json)", file=sys.stderr)
    print(json.dumps(report, indent=1))
    if not args.no_artifact:
        from tools.artifacts import write_json
        write_json(args.out, report)
        print(f"committed {args.out}", file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
