#!/usr/bin/env python
"""chaos_replay: re-run a chaos scenario from a recorded fault plan.

Every chaos scenario (tests/test_chaos.py) takes its faults from a plan
dict — `{site: {"seed": int, "specs": [{kind, p, n, ...}]}}` — armed on
the failpoint registry (dynamo_tpu/runtime/faults.py). The same plan
replays the same faults in the same order, so a failure seen once is a
failure you can hand someone as a JSON file.

Usage:
    python tools/chaos_replay.py --list
        name the scenarios (no heavy imports — safe for shell tabbing)
    python tools/chaos_replay.py <scenario> --dump-plan
        print the committed default plan JSON (edit it, feed it back)
    python tools/chaos_replay.py <scenario> [--plan plan.json]
        run the scenario under the given (or default) plan; the
        scenario's own assertions are the pass/fail contract
    python tools/chaos_replay.py <scenario> --record
        also append {scenario, plan, summary} to CHAOS_REPLAY.jsonl —
        append-only, final name, via tools/artifacts.py (the
        evidence-write policy: re-runs add records, never rewrite)

Exit code 0 on a clean run, 1 on a contract violation (AssertionError),
2 on usage errors.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Kept in sync with tests/test_chaos.py SCENARIOS (tests/test_faults.py
# asserts the two lists match) so --list never imports jax/the engine.
SCENARIO_NAMES = (
    "aggregated_zero_drop",
    "disagg_prefill_death",
    "disagg_transfer_storm",
    "rolling_restart",
    "control_plane_storm",
    "pool_host_storm",
    "fail_slow_storm",
)

DEFAULT_LOG = os.path.join(REPO_ROOT, "CHAOS_REPLAY.jsonl")


def _load_scenarios():
    """Heavy import (jax + engine), deferred past --list/--help."""
    sys.path.insert(0, REPO_ROOT)
    sys.path.insert(0, os.path.join(REPO_ROOT, "tests"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import test_chaos
    return test_chaos


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="chaos_replay", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("scenario", nargs="?", choices=SCENARIO_NAMES,
                    help="scenario to run (see --list)")
    ap.add_argument("--list", action="store_true",
                    help="list scenario names and exit")
    ap.add_argument("--plan", metavar="PLAN_JSON",
                    help="fault plan JSON file ({site: {seed, specs}}); "
                         "default: the scenario's committed plan")
    ap.add_argument("--dump-plan", action="store_true",
                    help="print the scenario's committed default plan "
                         "and exit (a starting point for --plan edits)")
    ap.add_argument("--record", action="store_true",
                    help=f"append the run record to {DEFAULT_LOG}")
    ap.add_argument("--record-to", default=DEFAULT_LOG,
                    help="append-only JSONL evidence log (default: "
                         "CHAOS_REPLAY.jsonl)")
    ap.add_argument("--trace", metavar="TRACE_JSONL", nargs="?",
                    const=os.path.join(REPO_ROOT, "CHAOS_TRACE.jsonl"),
                    help="capture spans during the storm (sample=1.0) and "
                         "append them to this JSONL (default: "
                         "CHAOS_TRACE.jsonl) plus a chrome://tracing twin "
                         "at <path>.chrome.json — both via "
                         "tools/artifacts.py; replay with "
                         "tools/trace_explain.py")
    args = ap.parse_args(argv)

    if args.list:
        for name in SCENARIO_NAMES:
            print(name)
        return 0
    if not args.scenario:
        ap.error("a scenario name (or --list) is required")

    test_chaos = _load_scenarios()
    assert set(test_chaos.SCENARIOS) == set(SCENARIO_NAMES), \
        "tools/chaos_replay.py SCENARIO_NAMES is stale vs tests/test_chaos"
    _, default_plan = test_chaos.SCENARIOS[args.scenario]

    if args.dump_plan:
        print(json.dumps(default_plan, indent=1))
        return 0

    plan = default_plan
    if args.plan:
        with open(args.plan) as f:
            plan = json.load(f)

    if args.trace:
        # a replayed storm should leave a DIAGNOSABLE artifact, not just
        # a pass/fail: sample everything, drain after the run
        from dynamo_tpu.runtime.tracing import TRACER
        TRACER.configure(enabled=True, sample_rate=1.0)
        TRACER.drain()  # start the capture clean

    started = time.time()
    try:
        summary = test_chaos.run_scenario(args.scenario, plan)
        ok, error = True, None
    except AssertionError as e:
        summary, ok, error = None, False, f"{e}"
    elapsed = time.time() - started

    record = {"scenario": args.scenario, "plan": plan, "ok": ok,
              "error": error, "summary": summary,
              "started_unix": round(started, 3),
              "elapsed_s": round(elapsed, 3)}
    if args.trace:
        from dynamo_tpu.runtime.tracing import TRACER, chrome_trace

        from tools.artifacts import append_jsonl, write_json
        spans = TRACER.drain()
        for span in spans:
            append_jsonl(args.trace, span)
        write_json(args.trace + ".chrome.json", chrome_trace(spans),
                   overwrite=True)
        record["trace_spans"] = len(spans)
        record["trace_file"] = args.trace
        print(f"captured {len(spans)} span(s) -> {args.trace} "
              f"(+ .chrome.json)", file=sys.stderr)
    print(json.dumps(record, indent=1))
    if args.record:
        from tools.artifacts import append_jsonl
        append_jsonl(args.record_to, record)
        print(f"recorded to {args.record_to}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
