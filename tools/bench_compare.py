#!/usr/bin/env python
"""bench_compare: the bench regression gate over BENCH_TRAJECTORY.jsonl.

The bench trajectory (BENCH_r0N.json wrappers) was unparseable by
downstream tooling: every run a differently-shaped blob, no machine
check that a PR regressed the headline number. bench.py now appends one
normalized row per supervised run to BENCH_TRAJECTORY.jsonl
(`bench.trajectory_row`); this tool diffs the LATEST MEASURED row per
metric against the gate table in BASELINE.json and exits nonzero on
regression — wired as a tier-1 test over the committed artifacts
(tests/test_bench_compare.py).

Semantics:

- a row with value <= 0 or extras.failure is an INFRASTRUCTURE-FAILED
  capture (the TPU tunnel never came up) — skipped, never a
  regression: it measures the tunnel, not the code;
- the gate table lives in BASELINE.json under "gates":
      {"<metric>": {"baseline": 81.33, "rel_tolerance": 0.25,
                    "direction": "higher"}}
  direction "higher" (default) fails when
      value < baseline * (1 - rel_tolerance);
  direction "lower" fails when value > baseline * (1 + rel_tolerance);
- a metric with no gate entry is compared against the PREVIOUS measured
  row of the same metric with --default-tolerance (trend gate);
- --backfill converts committed BENCH_r0N.json supervisor wrappers into
  trajectory rows (the one-time migration of the historical trail).

Exit codes: 0 ok / within tolerance; 1 regression; 2 no usable data.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def load_rows(path: str) -> List[dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("metric"):
                rows.append(rec)
    return rows


def measured(row: dict) -> bool:
    """A row that actually measured the code (vs. a failed capture)."""
    if float(row.get("value") or 0.0) <= 0.0:
        return False
    return "failure" not in (row.get("extras") or {})


def latest_measured(rows: List[dict]) -> Dict[str, List[dict]]:
    """metric -> measured rows in file (= time) order."""
    by_metric: Dict[str, List[dict]] = {}
    for row in rows:
        if measured(row):
            by_metric.setdefault(row["metric"], []).append(row)
    return by_metric


def check_metric(metric: str, rows: List[dict], gate: Optional[dict],
                 default_tolerance: float) -> dict:
    """One metric's verdict dict; 'status' in ok|regression|skipped."""
    latest = rows[-1]
    value = float(latest["value"])
    if gate is not None:
        baseline = float(gate["baseline"])
        tol = float(gate.get("rel_tolerance", default_tolerance))
        direction = gate.get("direction", "higher")
        source = "baseline"
    elif len(rows) >= 2:
        baseline = float(rows[-2]["value"])
        tol = default_tolerance
        direction = "higher"
        source = f"previous row ({rows[-2].get('run_id')})"
    else:
        return {"metric": metric, "status": "skipped",
                "reason": "no gate entry and no prior measured row",
                "value": value}
    if direction == "higher":
        floor = baseline * (1.0 - tol)
        ok = value >= floor
        bound = {"floor": round(floor, 4)}
    else:
        ceil = baseline * (1.0 + tol)
        ok = value <= ceil
        bound = {"ceiling": round(ceil, 4)}
    return {"metric": metric,
            "status": "ok" if ok else "regression",
            "value": value, "baseline": baseline,
            "rel_tolerance": tol, "direction": direction,
            "source": source, "run_id": latest.get("run_id"), **bound}


def compare(trajectory_path: str, baseline_path: str,
            default_tolerance: float = 0.25) -> dict:
    rows = load_rows(trajectory_path)
    with open(baseline_path) as f:
        gates = (json.load(f).get("gates") or {})
    by_metric = latest_measured(rows)
    skipped_captures = sum(1 for r in rows if not measured(r))
    results = [check_metric(metric, mrows, gates.get(metric),
                            default_tolerance)
               for metric, mrows in sorted(by_metric.items())]
    # a gate whose metric never produced a measured row is surfaced
    # (the gate exists because the number matters; silence would read
    # as "covered")
    for metric in sorted(set(gates) - set(by_metric)):
        results.append({"metric": metric, "status": "skipped",
                        "reason": "gated metric has no measured row"})
    return {
        "rows": len(rows),
        "skipped_failed_captures": skipped_captures,
        "results": results,
        "regressions": [r for r in results
                        if r["status"] == "regression"],
        "ok": bool(results) and not any(
            r["status"] == "regression" for r in results),
    }


def backfill(out_path: str, wrappers: List[str]) -> int:
    """Convert committed BENCH_r0N.json supervisor wrappers into
    trajectory rows (their 'parsed' field is the final result line)."""
    sys.path.insert(0, REPO_ROOT)
    from bench import trajectory_row

    from tools.artifacts import append_jsonl
    n = 0
    for path in wrappers:
        with open(path) as f:
            wrapper = json.load(f)
        parsed = wrapper.get("parsed")
        if not isinstance(parsed, dict) or not parsed.get("metric"):
            print(f"skip {path}: no parsed result", file=sys.stderr)
            continue
        run_id = os.path.splitext(os.path.basename(path))[0]
        append_jsonl(out_path, trajectory_row(parsed, run_id=run_id))
        n += 1
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_compare", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--trajectory",
                    default=os.path.join(REPO_ROOT,
                                         "BENCH_TRAJECTORY.jsonl"))
    ap.add_argument("--baseline",
                    default=os.path.join(REPO_ROOT, "BASELINE.json"))
    ap.add_argument("--default-tolerance", type=float, default=0.25,
                    help="relative tolerance for ungated trend checks")
    ap.add_argument("--backfill", nargs="+", metavar="BENCH_rNN.json",
                    help="append trajectory rows converted from "
                         "committed supervisor wrappers, then exit")
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    if args.backfill:
        n = backfill(args.trajectory, args.backfill)
        print(f"backfilled {n} row(s) -> {args.trajectory}")
        return 0 if n else 2

    if not os.path.exists(args.trajectory):
        print(f"no trajectory at {args.trajectory}", file=sys.stderr)
        return 2
    report = compare(args.trajectory, args.baseline,
                     args.default_tolerance)
    if not args.quiet:
        print(json.dumps(report, indent=1))
    if not report["results"]:
        print("no measured rows to gate on", file=sys.stderr)
        return 2
    if report["regressions"]:
        for r in report["regressions"]:
            print(f"REGRESSION {r['metric']}: {r['value']} vs "
                  f"{r['source']} {r['baseline']} "
                  f"(tolerance {r['rel_tolerance']})", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
