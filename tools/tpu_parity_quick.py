"""Standalone TPU numerical-parity runner (VERDICT r4 #2/#3).

Runs ONLY bench.py's parity phase (bench.run_parity — one shared
implementation, so this always validates the exact configuration the
bench measures) without the perf phases in front of it, so it fits a
short tunnel up-window: window engine (decode_steps=64, split-KV
pregather + deferred writeback + adaptive ladder) vs the single-step
twin, 96 greedy tokens, token-for-token. CPU tests can't see
Mosaic/XLA-TPU divergence — this is the one check that must execute on
hardware.

Rides the persistent compilation cache bench.py populates (.jax_cache),
so a run right after a bench capture only pays the single-step twin's
compile. Writes PARITY_TPU_r05.json and exits 0 on exact parity, 1 on
divergence, 2 when the backend never came up (caller retries later),
3 on a configuration error (permanent; never retried).

Reference bar: the window decode path is our throughput headline
(docs/architecture.md:57-61 analogue); an unnoticed numerics divergence
there would invalidate it.
"""
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)
# PARITY_OUT: alternate artifact name so variant captures (e.g. the int8
# parity item in tools/tpu_window_watch.sh's ladder) don't overwrite the
# bf16 evidence
OUT = os.path.join(HERE, os.environ.get("PARITY_OUT",
                                        "PARITY_TPU_r05.json"))


def log(*a):
    print("[parity]", *a, file=sys.stderr, flush=True)


def main() -> int:
    t0 = time.time()
    import jax
    # the image pins jax_platforms to the TPU tunnel programmatically;
    # honor an explicit JAX_PLATFORMS override (CPU validation runs)
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(HERE, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass
    devices = jax.devices()
    backend = jax.default_backend()
    log(f"backend up in {time.time() - t0:.1f}s: {devices} ({backend})")
    if backend != "tpu" and os.environ.get("PARITY_ALLOW_CPU") != "1":
        log("not a TPU backend; refusing (set PARITY_ALLOW_CPU=1 to force)")
        return 2

    import bench
    from dynamo_tpu.engine.config import get_model_config

    model_cfg = get_model_config(os.environ.get("BENCH_MODEL", "llama3-1b"))
    # honor BENCH_QUANT exactly as the bench worker does, so an int8
    # capture can get int8 parity evidence (not a bf16 run mislabeled)
    quant = os.environ.get("BENCH_QUANT", "")
    if quant:
        if quant != "int8":
            log(f"BENCH_QUANT={quant!r} unsupported (supported: int8)")
            return 3  # config error: permanent, never retried
        import dataclasses
        model_cfg = dataclasses.replace(model_cfg, quant=quant)
    # PARITY_DECODE_KERNEL=on: run the window-vs-single-step check with the
    # ragged Pallas decode kernel instead of the serving-default XLA gather
    # (models/llama._decode_kernel_mode), so the kernel path gets its own
    # token-for-token hardware evidence (PARITY_TPU_r18_ragged ladder item).
    dk = os.environ.get("PARITY_DECODE_KERNEL", "")
    if dk:
        if dk not in ("on", "interpret"):
            log(f"PARITY_DECODE_KERNEL={dk!r} unsupported "
                "(supported: on, interpret)")
            return 3
        import dataclasses
        model_cfg = dataclasses.replace(model_cfg, decode_kernel=dk)
    # PARITY_KV_QUANT=int8: run the kv-cache quantization gate instead of
    # the window-vs-single-step check — greedy-match rate + bounded logit
    # drift between the int8-KV engine and its unquantized twin, the SAME
    # bench.run_kv_quant_parity implementation (and thresholds) the tier-1
    # gate runs on CPU (tests/test_kv_quant.py), now on real hardware
    # (PARITY_TPU_r06_kvq ladder item).
    kvq = os.environ.get("PARITY_KV_QUANT", "")
    if kvq:
        if kvq != "int8":
            log(f"PARITY_KV_QUANT={kvq!r} unsupported (supported: int8)")
            return 3
        verdict = bench.run_kv_quant_parity(model_cfg, logf=log)
    else:
        verdict = bench.run_parity(model_cfg, logf=log)
    record = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": backend, "devices": [str(d) for d in devices],
        "parity": verdict, "window_decode_steps": 64,
        "elapsed_s": round(time.time() - t0, 1),
    }
    if quant:
        record["quant"] = quant
    if dk:
        record["decode_kernel"] = dk
    if kvq:
        record["kv_quant"] = kvq
    # evidence-artifact policy (tools/artifacts.py, VERDICT r5 weak #7):
    # final name, written once; a re-run of the same capture overwrites
    # deliberately rather than renaming the old file aside
    from tools.artifacts import write_json
    write_json(OUT, record, overwrite=True)
    log(f"wrote {OUT}")
    if kvq:
        return 0 if verdict.get("pass") else 1
    return 0 if verdict.startswith("exact") else 1


if __name__ == "__main__":
    sys.exit(main())
