"""Standalone TPU numerical-parity runner (VERDICT r4 #2/#3).

Mirrors bench.py's parity phase without the perf phases in front of it,
so it fits a short tunnel up-window: build the flagship window engine
(decode_steps=64, split-KV pregather + deferred writeback + adaptive
ladder), greedy-generate 96 tokens, rebuild as the single-step twin
(decode_steps=1, same seed => identical params), and assert the token
streams are identical. CPU tests can't see Mosaic/XLA-TPU divergence —
this is the one check that must execute on hardware.

Rides the persistent compilation cache bench.py populates (.jax_cache),
so a run right after a bench capture only pays the single-step twin's
compile. Writes PARITY_TPU_r05.json and exits 0 on exact parity, 1 on
divergence, 2 when the backend never came up (caller retries later).

Reference bar: the window decode path is our throughput headline
(docs/architecture.md:57-61 analogue); an unnoticed numerics divergence
there would invalidate it.
"""
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)
OUT = os.path.join(HERE, "PARITY_TPU_r05.json")


def log(*a):
    print("[parity]", *a, file=sys.stderr, flush=True)


def main() -> int:
    t0 = time.time()
    import jax
    # the image pins jax_platforms to the TPU tunnel programmatically;
    # honor an explicit JAX_PLATFORMS override (CPU validation runs)
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        try:
            jax.config.update("jax_platforms", want)
        except Exception:
            pass
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(HERE, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass
    devices = jax.devices()
    backend = jax.default_backend()
    log(f"backend up in {time.time() - t0:.1f}s: {devices} ({backend})")
    if backend != "tpu" and os.environ.get("PARITY_ALLOW_CPU") != "1":
        log("not a TPU backend; refusing (set PARITY_ALLOW_CPU=1 to force)")
        return 2

    from dynamo_tpu.engine.config import EngineConfig, get_model_config
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import SamplingParams

    model_cfg = get_model_config(os.environ.get("BENCH_MODEL", "llama3-1b"))
    prompt = [(31 * j) % 1000 + 1 for j in range(64)]
    params = SamplingParams(max_tokens=96, temperature=0.0, ignore_eos=True)

    def build(decode_steps):
        cfg = EngineConfig(
            page_size=64, num_pages=256, max_slots=8, max_prefill_chunk=128,
            prefill_buckets=(128,), max_model_len=2048,
            decode_steps=decode_steps, max_prefill_batch=8)
        return NativeEngine(model_cfg, cfg, seed=0)

    log("building window engine (decode_steps=64)")
    engine = build(64)
    t1 = time.time()
    got = engine.generate(prompt, params, "parity-window")
    log(f"window side: {len(got)} tokens in {time.time() - t1:.1f}s")
    del engine  # free HBM before the twin

    log("building single-step twin (decode_steps=1)")
    e1 = build(1)
    t2 = time.time()
    ref = e1.generate(prompt, params, "parity-single")
    log(f"single-step side: {len(ref)} tokens in {time.time() - t2:.1f}s")

    if got == ref:
        verdict = f"exact({len(ref)} tokens)"
        rc = 0
        log(f"parity OK: {len(ref)} greedy tokens identical")
    else:
        div = next((i for i, (a, b) in enumerate(zip(got, ref))
                    if a != b), min(len(got), len(ref)))
        verdict = f"DIVERGED@{div}"
        rc = 1
        log(f"parity FAILURE at token {div}: window={got[:div + 3]} "
            f"single={ref[:div + 3]}")
    json.dump({
        "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": backend, "devices": [str(d) for d in devices],
        "parity": verdict, "tokens": len(ref),
        "window_decode_steps": 64, "elapsed_s": round(time.time() - t0, 1),
    }, open(OUT, "w"), indent=1)
    log(f"wrote {OUT}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
