"""Real-checkpoint serving evidence (VERDICT r4 #4).

Builds a GENUINE HuggingFace checkpoint on disk — a transformers
LlamaForCausalLM (seeded) saved with save_pretrained + a byte-level BPE
tokenizer.json trained with the `tokenizers` library — then serves it
through the FULL stack with the one-command launcher
(`python -m dynamo_tpu.run in=http:<port> out=native <dir>`:
HTTP -> preprocessor -> HF tokenizer -> NativeEngine -> incremental
detokenizer -> SSE), and asserts the streamed greedy completion is
IDENTICAL to `transformers` `generate()` on the same checkpoint. Records
TTFT and the JAX backend in the committed log.

No pretrained weights ship in this image (zero egress), so "real" here
means full checkpoint fidelity: the exact safetensors/config/tokenizer
file formats a user points the launcher at, loaded by the same code path
(`ModelDeploymentCard.from_hf_dir` + `load_params_from_hf`) that loads
Llama-3 checkpoints, with transformers as the independent oracle.
Reference analogue: launch/dynamo-run serving a hub checkpoint
(launch/dynamo-run/src/hub.rs).

Run: python tools/real_ckpt_e2e.py [--out LOG]
(JAX_PLATFORMS=cpu for the CPU fallback; under the axon tunnel it runs
on the TPU backend — the backend lands in the log either way.)
"""
import argparse
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PROMPT = "The quick brown fox jumps over the lazy dog. "
MAX_NEW = 32
CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump",
    "sphinx of black quartz judge my vow",
    "a journey of a thousand miles begins with a single step",
] * 20


def build_checkpoint(path: str) -> None:
    import torch
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, trainers
    from transformers import LlamaConfig, LlamaForCausalLM

    tok = Tokenizer(models.BPE())
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    tok.train_from_iterator(
        CORPUS, trainers.BpeTrainer(
            vocab_size=512, special_tokens=["</s>"],
            initial_alphabet=pre_tokenizers.ByteLevel.alphabet()))
    os.makedirs(path, exist_ok=True)
    tok.save(os.path.join(path, "tokenizer.json"))

    torch.manual_seed(7)
    cfg = LlamaConfig(
        vocab_size=tok.get_vocab_size(), hidden_size=256,
        intermediate_size=688, num_hidden_layers=4, num_attention_heads=8,
        num_key_value_heads=4, max_position_embeddings=2048,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
        eos_token_id=tok.token_to_id("</s>"), bos_token_id=None,
        attention_bias=False, torch_dtype="float32")
    model = LlamaForCausalLM(cfg)
    # overfit the tiny model on the corpus so greedy continuations are
    # recognizable English, not random bytes — the committed log then
    # shows REAL trained weights producing sensible text end-to-end
    ids = tok.encode(" ".join(CORPUS[:5]) + " ").ids * 8
    chunk = 64
    batch = torch.tensor([ids[i:i + chunk]
                          for i in range(0, len(ids) - chunk, chunk // 2)])
    opt = torch.optim.AdamW(model.parameters(), lr=3e-3)
    model.train()
    for step in range(120):
        opt.zero_grad()
        out = model(batch, labels=batch)
        out.loss.backward()
        opt.step()
        if out.loss.item() < 0.05:
            break
    print(f"[e2e] trained {step + 1} steps, loss {out.loss.item():.3f}",
          flush=True)
    model.eval()
    model.save_pretrained(path, safe_serialization=True)


def oracle_continuation(path: str) -> str:
    import torch
    from tokenizers import Tokenizer
    from transformers import LlamaForCausalLM

    tok = Tokenizer.from_file(os.path.join(path, "tokenizer.json"))
    model = LlamaForCausalLM.from_pretrained(path).eval()
    ids = tok.encode(PROMPT).ids
    with torch.no_grad():
        out = model.generate(
            torch.tensor([ids]), do_sample=False, max_new_tokens=MAX_NEW,
            eos_token_id=tok.token_to_id("</s>"), pad_token_id=0)
    return tok.decode(out[0][len(ids):].tolist())


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def serve_and_query(path: str, extra_args: tuple = ()):
    """One-command launch, then a streamed /v1/completions request.
    Returns (text, ttft_ms, model_name)."""
    import threading

    port = free_port()
    env = {**os.environ, "PYTHONPATH": REPO}
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.run", f"in=http:{port}",
         "out=native", path, "--num-pages", "64", "--max-slots", "4",
         *extra_args],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, cwd=REPO,
        env=env, text=True)
    model_name = None
    # a server that hangs producing no stdout would block readline()
    # forever; the timer turns that into EOF -> RuntimeError below
    watchdog = threading.Timer(600, proc.kill)
    watchdog.start()
    try:
        while True:
            line = proc.stdout.readline()
            if not line and proc.poll() is not None:
                raise RuntimeError("server exited (or hung past the "
                                   "watchdog) before READY")
            if line.startswith("READY"):
                model_name = line.split("model=")[1].strip()
                break
        body = json.dumps({
            "model": model_name, "prompt": PROMPT, "stream": True,
            "max_tokens": MAX_NEW, "temperature": 0.0}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.time()
        ttft_ms = None
        text = []
        with urllib.request.urlopen(req, timeout=300) as resp:
            for raw in resp:
                raw = raw.decode().strip()
                if not raw.startswith("data:"):
                    continue
                payload = raw[5:].strip()
                if payload == "[DONE]":
                    break
                chunk = json.loads(payload)
                piece = chunk["choices"][0].get("text", "")
                if piece and ttft_ms is None:
                    ttft_ms = (time.time() - t0) * 1000
                text.append(piece)
        return "".join(text), ttft_ms, model_name
    finally:
        watchdog.cancel()
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "real_ckpt_e2e.log"))
    ap.add_argument("--dir", default="/tmp/real_ckpt_e2e_model")
    args = ap.parse_args()

    print(f"[e2e] building real HF checkpoint in {args.dir}", flush=True)
    build_checkpoint(args.dir)
    print("[e2e] transformers oracle generate()", flush=True)
    expect = oracle_continuation(args.dir)
    print(f"[e2e] oracle: {expect!r}", flush=True)
    print("[e2e] serving via `python -m dynamo_tpu.run in=http "
          "out=native` and streaming a completion", flush=True)
    got, ttft_ms, model_name = serve_and_query(args.dir)
    print(f"[e2e] served: {got!r} (ttft "
          f"{'n/a' if ttft_ms is None else f'{ttft_ms:.1f} ms'})",
          flush=True)
    # speculative decoding on real weights: same stack with prompt-lookup
    # drafts must stream the IDENTICAL text (engine/spec.py exactness on a
    # genuine checkpoint, not just the random-weight unit tests)
    print("[e2e] re-serving with --spec-decode ngram", flush=True)
    spec_got, spec_ttft_ms, _ = serve_and_query(
        args.dir, ("--spec-decode", "ngram"))
    spec_ok = spec_got == got
    print(f"[e2e] spec-decode text "
          f"{'matches' if spec_ok else 'DIVERGES: ' + repr(spec_got)}",
          flush=True)
    # determine the backend the server actually used AFTER it exited —
    # initializing jax in this parent while the server runs would
    # contend for the single-slot TPU tunnel. The probe must re-assert
    # JAX_PLATFORMS after import (this image's sitecustomize re-pins the
    # tunnel programmatically; the env var alone is ignored).
    probe = ("import os, jax\n"
             "w = os.environ.get('JAX_PLATFORMS')\n"
             "if w:\n"
             "    jax.config.update('jax_platforms', w)\n"
             "print(jax.default_backend())")
    try:
        backend = subprocess.run(
            [sys.executable, "-c", probe], capture_output=True,
            text=True, timeout=300).stdout.strip() or "?"
    except subprocess.TimeoutExpired:
        backend = "? (backend probe timed out)"
    ok = got == expect
    record = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": backend, "model": model_name, "prompt": PROMPT,
        "tokens": MAX_NEW,
        "ttft_ms": None if ttft_ms is None else round(ttft_ms, 1),
        "match": ok, "text": got,
        "oracle": expect if not ok else None,
        "spec_decode_match": spec_ok,
        "spec_ttft_ms": (None if spec_ttft_ms is None
                         else round(spec_ttft_ms, 1)),
    }
    with open(args.out, "a") as f:
        f.write(json.dumps(record) + "\n")
    # spec divergence on CPU/f32 is a real bug (both paths lower to the
    # same arithmetic); on TPU bf16 a near-tie argmax flip between the
    # verify and decode programs is the documented caveat (engine/spec.py)
    # — record it, but do not fail the run or the watch loop would
    # discard valid base evidence and rebuild forever (code-review r5)
    spec_gates = spec_ok or backend == "tpu"
    print(f"[e2e] {'PASS' if ok and spec_gates else 'FAIL'}: full-stack "
          f"greedy text {'matches' if ok else 'DIVERGES from'} "
          f"transformers on backend={backend}; spec-decode pass "
          f"{'matches' if spec_ok else 'diverges (near-tie caveat on tpu; a BUG on cpu)'}; "
          f"log -> {args.out}", flush=True)
    sys.exit(0 if ok and spec_gates else 1)


if __name__ == "__main__":
    main()
