#!/usr/bin/env python
"""fleet_top: render the fleet time-series rollup, one-shot or --watch.

The `top(1)` of the telemetry plane (docs/OBSERVABILITY.md §6): given a
live coordinator it stands up a FleetRollup (observability/fleet.py)
over the worker `$STATS` plane, scrapes, and renders the fleet
aggregates, per-worker table, per-link KV-transfer bandwidth EWMAs and
(optionally) SLO burn state. Given a committed evidence artifact
(--from-artifact FLEET_r10.json) it renders the same view offline from
the recorded summaries — the review path for a storm that already
happened.

Usage:
    python tools/fleet_top.py --coordinator 127.0.0.1:6230 \
        --namespace ns --component worker [--watch] [--interval 2]
    python tools/fleet_top.py --from-artifact FLEET_r10.json
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
from typing import Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _fmt(v, nd=2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def render_summary(summary: dict, slo: Optional[dict] = None,
                   workers: Optional[dict] = None) -> str:
    """Pure renderer over FleetRollup.summary() output (+ optional
    SloWatchdog.summary() and a per-worker last-value table) — the same
    function drives --watch, one-shot, and --from-artifact, and the
    tier-1 smoke golden-checks it."""
    out = [f"fleet @ ts={_fmt(summary.get('ts'))}  "
           f"scrapes={summary.get('scrapes')}  "
           f"workers_seen={summary.get('workers_seen')}"]
    fleet = summary.get("fleet") or {}
    if fleet:
        out.append("  fleet (last / avg / max over window):")
        for name, agg in sorted(fleet.items()):
            if agg is None:
                continue
            out.append(f"    {name:<20} {_fmt(agg.get('last')):>10} "
                       f"{_fmt(agg.get('avg')):>10} "
                       f"{_fmt(agg.get('max')):>10}")
    roles = summary.get("roles") or {}
    if roles:
        # the prefill/decode split the autoscaler steers (rollup
        # `role/*` series; older artifacts carry no roles -> omitted)
        out.append("  roles:")
        for role, fields in sorted(roles.items()):
            vals = {k: (a or {}).get("last") for k, a in fields.items()}
            out.append(
                f"    {role:<10} workers={_fmt(vals.get('workers'), 0)} "
                f"draining={_fmt(vals.get('draining'), 0)} "
                f"queue={_fmt(vals.get('queue_depth'), 1)} "
                f"occ={_fmt(vals.get('occupancy'))} "
                f"avail={_fmt(vals.get('availability'))}")
    serving = summary.get("serving") or {}
    for name, agg in sorted(serving.items()):
        if agg:
            out.append(f"  serving/{name}: last={_fmt(agg.get('last'), 4)} "
                       f"avg={_fmt(agg.get('avg'), 4)}")
    qos = summary.get("qos") or {}
    if qos:
        # per-tenant-class serving split (rollup `qos/*` series from
        # the class-labeled histograms; older artifacts omit it)
        out.append("  qos classes (last):")
        for cls, fields in sorted(qos.items()):
            vals = {k: (a or {}).get("last") for k, a in fields.items()}
            out.append(
                f"    {cls:<12} ttft_p95={_fmt(vals.get('ttft_p95'), 4)} "
                f"itl_p99={_fmt(vals.get('itl_p99'), 4)} "
                f"queue_p95={_fmt(vals.get('queue_wait_p95'), 4)}")
    cp = summary.get("cp") or {}
    if cp:
        vals = {k: (a or {}).get("last") for k, a in cp.items()}
        out.append(f"  control plane: degraded="
                   f"{_fmt(vals.get('router_degraded'), 0)} "
                   f"event_lag={_fmt(vals.get('event_lag_seconds'), 3)}s")
    health = summary.get("health") or {}
    if health.get("workers"):
        # fail-slow plane (runtime/health.py): fleet-relative scores in
        # [0, 1], SLOW workers marked. Older artifacts carry no health
        # key -> section omitted (renderers must tolerate that).
        slow = set(health.get("slow") or ())
        rows = sorted(health["workers"].items(),
                      key=lambda kv: (kv[1].get("score", 1.0), kv[0]))
        out.append(f"  fail-slow health ({len(rows)} scored, "
                   f"{len(slow)} slow):")
        for wid, row in rows[:16]:
            mark = " SLOW" if wid in slow else ""
            out.append(f"    {wid:<12} score={_fmt(row.get('score'))} "
                       f"z={_fmt(row.get('z'))} "
                       f"n={_fmt(row.get('n'), 0)}{mark}")
        if len(rows) > 16:
            out.append(f"    ... {len(rows) - 16} more")
        hed = health.get("hedges") or {}
        if hed:
            out.append(
                f"    hedges: fired={_fmt(hed.get('fired'), 0)} "
                f"won={_fmt(hed.get('wins'), 0)} "
                f"lost={_fmt(hed.get('losses'), 0)} "
                f"budget_denied={_fmt(hed.get('budget_denied'), 0)} "
                f"suppressed_commit="
                f"{_fmt(hed.get('suppressed_commit'), 0)}")
    links = summary.get("links") or {}
    if links:
        out.append(f"  kv-transfer links ({len(links)} measured):")
        for link, snap in sorted(links.items()):
            mbs = snap["bytes_per_s"] / 1e6
            # estimator error (signed EWMA of est-vs-actual transfer
            # time; TransferCostModel): negative = the bandwidth EWMA
            # is stale-fast and the router under-prices this link.
            # Older artifacts carry no err field -> "-" (unchanged).
            err = snap.get("est_err_frac")
            err_txt = f" err {err * 100:+.1f}%" if err is not None else ""
            backlog = snap.get("backlog_bytes")
            bl_txt = f" backlog {backlog >> 20}MiB" if backlog else ""
            out.append(f"    {link:<24} {mbs:10.1f} MB/s "
                       f"({snap['samples']} samples){err_txt}{bl_txt}")
    streams = summary.get("xfer_streams") or {}
    if streams:
        # sharded parallel transfer: per-(shard, host) stream rows.
        # The request-wide committed frontier is the MIN over a
        # transfer's streams, so the stream pinning the min per engine
        # prefix is flagged as the straggler — the first thing to look
        # at when disagg TTFT regresses on a multi-host mesh.
        mins: dict = {}
        for skey, row in streams.items():
            eng = skey.split("/", 1)[0]
            cur = mins.get(eng)
            if cur is None or row.get("frontier", 0) < cur[1]:
                mins[eng] = (skey, row.get("frontier", 0))
        out.append(f"  kv-transfer streams ({len(streams)}):")
        for skey, row in sorted(streams.items()):
            eng = skey.split("/", 1)[0]
            straggler = " <- min-frontier straggler" \
                if mins.get(eng, ("",))[0] == skey \
                and len([s for s in streams if
                         s.split('/', 1)[0] == eng]) > 1 else ""
            out.append(
                f"    {skey:<24} frontier={row.get('frontier', 0):<5}"
                f" pages={row.get('pages', 0):<7}"
                f" bytes={row.get('bytes', 0):<12}"
                f" resumes={row.get('resumes', 0)}{straggler}")
    if slo:
        out.append("  slo burn:")
        for name, st in sorted(slo.items()):
            mark = "FIRING" if st.get("firing") else "ok"
            out.append(
                f"    {name:<24} {mark:<7} "
                f"short={_fmt(st.get('burn_short'))} "
                f"long={_fmt(st.get('burn_long'))} "
                f"transitions={st.get('transitions', 0)}")
    if workers:
        out.append(f"  workers ({len(workers)}):")
        for wid, row in sorted(workers.items())[:32]:
            out.append(f"    {wid:<12} " + " ".join(
                f"{k}={_fmt(v)}" for k, v in sorted(row.items())))
        if len(workers) > 32:
            out.append(f"    ... {len(workers) - 32} more")
    return "\n".join(out)


def render_artifact(report: dict) -> str:
    """Offline view of a committed FLEET_r10-style artifact."""
    out = [f"artifact: seed={report.get('seed')} "
           f"workers={report.get('workers')} "
           f"ok={report.get('ok')}"]
    for phase in ("healthy", "storm", "recovered"):
        snap = (report.get("rollup") or {}).get(phase)
        if snap:
            out.append(f"--- {phase} ---")
            out.append(render_summary(snap, slo=(report.get("slo_states")
                                                 or {}).get(phase)))
    alerts = report.get("alerts") or []
    if alerts:
        out.append("alert timeline:")
        for ev in alerts:
            out.append(f"  t={_fmt(ev.get('ts'))} {ev.get('event'):>5} "
                       f"{ev.get('slo')} burn_short="
                       f"{_fmt(ev.get('burn_short'))} "
                       f"burn_long={_fmt(ev.get('burn_long'))}")
    ledger = report.get("ledger")
    if ledger:
        out.append(f"engine ledger: {ledger.get('samples')} samples "
                   f"({ledger.get('jsonl')}), "
                   f"pad_waste={_fmt(ledger.get('pad_waste_frac'), 3)}, "
                   f"recompiles={ledger.get('recompiles')}")
    contracts = report.get("contracts")
    if contracts:
        out.append("contracts: " + " ".join(
            f"{k}={'PASS' if v else 'FAIL'}"
            for k, v in sorted(contracts.items())))
    return "\n".join(out)


async def _live(args) -> int:
    from dynamo_tpu.observability.fleet import FleetRollup
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    host, port = args.coordinator.rsplit(":", 1)
    runtime = await DistributedRuntime.connect(host, int(port), "fleet-top")
    ep = runtime.namespace(args.namespace).component(
        args.component).endpoint(args.endpoint)
    client = ep.client()
    await client.start()
    # the watch needs a beat to deliver the instance set — scraping
    # before it lands renders an empty fleet and reads as an outage
    try:
        await client.wait_for_instances(timeout=5.0)
    except Exception:
        pass    # an actually-empty fleet still renders (as empty)
    rollup = FleetRollup(client, interval_s=args.interval)
    try:
        while True:
            await rollup.scrape_once()
            workers = {}
            for name in rollup.store.names("worker/"):
                _, wid, field = name.split("/", 2)
                if field in ("kv_active_blocks", "engine_tok_s",
                             "num_requests_waiting"):
                    workers.setdefault(wid, {})[field] = \
                        rollup.store.get(name).latest()
            print(render_summary(rollup.summary(), workers=workers),
                  flush=True)
            if not args.watch:
                return 0
            print("", flush=True)
            await asyncio.sleep(args.interval)
    finally:
        await client.stop()
        await runtime.shutdown()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet_top", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--coordinator", default="127.0.0.1:6230")
    ap.add_argument("--namespace", default="dynamo")
    ap.add_argument("--component", default="worker")
    ap.add_argument("--endpoint", default="generate")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--watch", action="store_true",
                    help="keep rendering every --interval seconds")
    ap.add_argument("--from-artifact", metavar="FLEET_JSON",
                    help="render a committed fleet evidence artifact "
                         "offline instead of scraping a live fleet")
    args = ap.parse_args(argv)
    if args.from_artifact:
        with open(args.from_artifact) as f:
            print(render_artifact(json.load(f)))
        return 0
    return asyncio.run(_live(args))


if __name__ == "__main__":
    sys.exit(main())
