"""KV-aware-routing TTFT evidence (BASELINE.md "KV-aware routing: TTFT 3x").

The reference's claim (reference: docs/architecture.md:87 — 3x TTFT, 2x
avg latency, 100K real R1 queries on 2 H100 nodes) rests on one
mechanism: multi-turn/shared-prefix traffic routed to the worker that
already holds the prefix KV skips recomputing it. This bench drives that
mechanism through OUR full stack — real control-plane server, N real
worker processes (`dynamo_tpu.run in=endpoint out=native`), the real
HTTP frontend + model watcher, llmctl registration — and A/Bs the same
multi-turn workload under:

  A) kv-routed registration (llmctl --kv-routed -> KvRouter cost
     function, reference scheduler.rs:290 recipe), vs
  B) locality-blind round-robin (the WorkerSink default).

Workload: C conversations, each with a fixed random token prefix
(token-array prompts, so token math is exact), T turns growing the
prompt each turn; conversation order is shuffled per turn so round-robin
can't accidentally align conversations to workers. Sequential streaming
requests; TTFT = send -> first SSE token chunk. Fresh worker processes
per mode (no cache bleed). Emits ROUTING_TTFT.json:
p50/mean TTFT per mode over turns >= 1 (turn 0 is cold everywhere) and
the improvement ratio.

Scale note: on CPU with the tiny model this demonstrates the mechanism,
not the reference's absolute numbers; on a TPU backend the same script
runs unchanged (prefill is bigger, the gap grows).

Run: python tools/routing_ttft_bench.py [--conversations 8 --turns 4
     --prefix-tokens 768 --out ROUTING_TTFT.json]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import signal
import socket
import statistics
import subprocess
import sys
import tempfile
import time
import urllib.request

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)


def log(*a):
    print("[routing-bench]", *a, file=sys.stderr, flush=True)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class Stack:
    """One serving stack: control plane + N workers + frontend.

    Shared by the routing and offload TTFT benches (tools/
    offload_ttft_bench.py imports it); worker_args appends to every
    worker's `dynamo_tpu.run` command line (e.g. --host-pages)."""

    def __init__(self, n_workers: int, kv_routed: bool, tag: str,
                 worker_args=(), logdir=None):
        self.procs = []
        self.kv_routed = kv_routed
        self.tag = tag
        self.n_workers = n_workers
        self.worker_args = list(worker_args)
        self.env = dict(os.environ, PYTHONPATH=HERE, JAX_PLATFORMS="cpu")
        self.cp_port = free_port()
        self.http_port = free_port()
        self.logdir = logdir or tempfile.mkdtemp(prefix=f"stack-{tag}-")
        self._n = 0

    def spawn(self, args, ready=None, timeout=180):
        # child output goes to a FILE (a pipe nobody drains would fill at
        # 64KB and block the child mid-bench); readiness is polled from
        # the file with a real deadline, so a silently-hung child raises
        # instead of blocking a readline forever
        self._n += 1
        logpath = os.path.join(self.logdir, f"proc{self._n}.log")
        logf = open(logpath, "w")
        p = subprocess.Popen(args, env=self.env, stdout=logf,
                             stderr=subprocess.STDOUT, cwd=HERE)
        logf.close()
        self.procs.append(p)
        if ready:
            t0 = time.time()
            while time.time() - t0 < timeout:
                with open(logpath) as f:
                    content = f.read()
                if ready in content:
                    return p
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{args[-3:]} died:\n{content[-2000:]}")
                time.sleep(0.3)
            raise RuntimeError(f"{args[-3:]}: no {ready!r} in {timeout}s")
        return p

    def start(self, data_dir: str):
        py = sys.executable
        self.spawn([py, "-m", "dynamo_tpu.runtime.transports.server",
                    "--port", str(self.cp_port), "--data-dir", data_dir])
        time.sleep(1.5)
        for i in range(self.n_workers):
            self.spawn(
                [py, "-m", "dynamo_tpu.run",
                 "in=endpoint:ns.worker.generate", "out=native", "tiny",
                 "--control-port", str(self.cp_port),
                 "--max-slots", "4",
                 *self.worker_args],
                ready="READY endpoint")
            log(f"[{self.tag}] worker {i} up")
        self.spawn([py, "-m", "dynamo_tpu.frontend.serve",
                    "--port", str(self.http_port),
                    "--control-port", str(self.cp_port)],
                   ready="READY http")
        reg = [py, "-m", "dynamo_tpu.llmctl",
               "--control-port", str(self.cp_port),
               "add", "tiny", "ns.worker.generate", "--arch", "tiny",
               "--model-type", "completion"]
        if self.kv_routed:
            reg.append("--kv-routed")
        subprocess.run(reg, env=self.env, check=True, capture_output=True,
                       cwd=HERE, timeout=60)
        # model watcher applies the registration asynchronously
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{self.http_port}/v1/models",
                        timeout=5) as r:
                    if b"tiny" in r.read():
                        return
            except Exception:
                pass
            time.sleep(0.5)
        raise RuntimeError("model never appeared in /v1/models")

    def request_ttft(self, token_prompt, max_tokens=8):
        """Streaming completion; returns (ttft_s, total_s)."""
        body = json.dumps({
            "model": "tiny", "prompt": token_prompt,
            "max_tokens": max_tokens, "stream": True,
            "temperature": 0.0}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{self.http_port}/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        t0 = time.perf_counter()
        ttft = None
        with urllib.request.urlopen(req, timeout=300) as r:
            for line in r:
                if line.startswith(b"data:") and b"[DONE]" not in line:
                    if ttft is None:
                        ttft = time.perf_counter() - t0
        if ttft is None:  # no token chunk at all: surface it at the request
            raise RuntimeError("stream carried no data chunks")
        return ttft, time.perf_counter() - t0

    def stop(self):
        for p in self.procs:
            try:
                os.kill(p.pid, signal.SIGKILL)
            except OSError:
                pass
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


def run_mode(kv_routed: bool, args, workdir: str) -> dict:
    tag = "kv" if kv_routed else "rr"
    stack = Stack(args.workers, kv_routed, tag,
                  worker_args=["--num-pages", str(args.num_pages)])
    rng = random.Random(1234)  # same workload both modes
    convs = [[rng.randrange(1, 1000) for _ in range(args.prefix_tokens)]
             for _ in range(args.conversations)]
    suffixes = [[[rng.randrange(1, 1000) for _ in range(args.suffix_tokens)]
                 for _ in range(args.turns)] for _ in range(args.conversations)]
    try:
        stack.start(os.path.join(workdir, tag))
        log(f"[{tag}] stack up (cp={stack.cp_port}, http={stack.http_port})")
        # Warmup epoch: replay the EXACT workload shape with throwaway
        # conversations so every XLA program variant the measurement will
        # hit compiles here, not inside a timed TTFT. The program key is
        # (batch bucket, token bucket, page-table bucket): a prefix-HIT
        # turn prefills only its uncached tail against a multi-page table
        # — a shape no fresh short prompt reaches. Each request is sent
        # TWICE back-to-back: under round-robin the pair lands on both
        # workers (so both cache every turn level and both compile every
        # hit-remainder shape); under kv-routing the duplicate follows
        # the prefix to the same worker and the workers*2 distinct
        # conversations spread coverage.
        for w in range(args.workers * 2):
            wrng = random.Random(7000 + w)
            base = [wrng.randrange(1, 1000)
                    for _ in range(args.prefix_tokens)]
            for t in range(args.turns + 1):
                prompt = base + [wrng.randrange(1, 1000)
                                 for _ in range(t * args.suffix_tokens)]
                stack.request_ttft(prompt, max_tokens=args.max_tokens)
                stack.request_ttft(prompt, max_tokens=args.max_tokens)
        log(f"[{tag}] warmup done ({args.workers * 2} throwaway convs x "
            f"{args.turns + 1} lengths x2)")
        per_turn = []
        per_turn_total = []
        for t in range(args.turns):
            # think-time between turns: real multi-turn traffic has it, and
            # it gives the async KV-event plane (worker -> control plane ->
            # router indexer) time to apply the previous turn's stores —
            # the reference's router consumes the same async event stream
            time.sleep(args.turn_gap_s)
            order = list(range(args.conversations))
            rng.shuffle(order)
            ttfts, totals = [], []
            for c in order:
                prompt = list(convs[c])
                for u in range(t + 1):
                    prompt += suffixes[c][u]
                ttft, total = stack.request_ttft(
                    prompt, max_tokens=args.max_tokens)
                ttfts.append(ttft)
                totals.append(total)
            per_turn.append(ttfts)
            per_turn_total.append(totals)
            log(f"[{tag}] turn {t}: p50 {statistics.median(ttfts)*1e3:.0f} ms")
        warm_ttfts = [x for turn in per_turn[1:] for x in turn]
        warm_totals = [x for turn in per_turn_total[1:] for x in turn]
        return {
            "mode": tag,
            "ttft_p50_ms": round(statistics.median(warm_ttfts) * 1e3, 1),
            "ttft_mean_ms": round(statistics.fmean(warm_ttfts) * 1e3, 1),
            # whole-request latency (send -> [DONE]): the reference's
            # companion claim is 2x AVG request latency (architecture
            # doc's routing figure), so record the mean as the headline
            "latency_mean_ms": round(statistics.fmean(warm_totals) * 1e3, 1),
            "latency_p50_ms": round(statistics.median(warm_totals) * 1e3, 1),
            "turn0_p50_ms": round(statistics.median(per_turn[0]) * 1e3, 1),
            "per_turn_p50_ms": [round(statistics.median(t) * 1e3, 1)
                                for t in per_turn],
            "raw_ttft_ms": [[round(x * 1e3, 1) for x in t]
                            for t in per_turn],
        }
    finally:
        stack.stop()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--conversations", type=int, default=8)
    ap.add_argument("--turns", type=int, default=4)
    ap.add_argument("--prefix-tokens", type=int, default=768)
    ap.add_argument("--suffix-tokens", type=int, default=16)
    ap.add_argument("--max-tokens", type=int, default=8)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="per-worker HBM pages; default sizes the pool so "
                    "ONE worker fits its kv-routed partition of the "
                    "conversations but NOT all of them — the regime the "
                    "routing claim is about (locality-blind routing "
                    "duplicates every conversation onto every worker and "
                    "thrashes; kv-routing partitions and fits)")
    ap.add_argument("--turn-gap-s", type=float, default=1.5)
    ap.add_argument("--out", default=os.path.join(HERE, "ROUTING_TTFT.json"))
    args = ap.parse_args()
    if args.num_pages is None:
        pages_per_conv = -(-(args.prefix_tokens + args.turns
                             * args.suffix_tokens + args.max_tokens
                             * args.turns) // 64) + 1
        args.num_pages = int(pages_per_conv
                             * (args.conversations / args.workers) * 1.6)

    with tempfile.TemporaryDirectory() as workdir:
        rr = run_mode(False, args, workdir)
        kv = run_mode(True, args, workdir)

    result = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "workload": {
            "conversations": args.conversations, "turns": args.turns,
            "prefix_tokens": args.prefix_tokens,
            "suffix_tokens": args.suffix_tokens,
            "max_tokens": args.max_tokens, "workers": args.workers,
            "num_pages_per_worker": args.num_pages,
            "turn_gap_s": args.turn_gap_s,
            "model": "tiny"},
        "round_robin": rr, "kv_routed": kv,
        "ttft_improvement": round(rr["ttft_p50_ms"] / kv["ttft_p50_ms"], 2)
        if kv["ttft_p50_ms"] else None,
        "latency_improvement": round(
            rr["latency_mean_ms"] / kv["latency_mean_ms"], 2)
        if kv["latency_mean_ms"] else None,
    }
    from tools.artifacts import write_json
    write_json(args.out, result, overwrite=True)  # final name, no renames
    log("wrote", args.out)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
