#!/usr/bin/env python
"""decode_profile: phase-attributed decode-loop profiling harness.

VERDICT r5 weak #2: decode throughput sat at ~60% (bf16) / ~45% (int8) of
the weight-bound roofline with the byte-independent remainder — host plan
building, per-window uploads, the blocking output fetch, commit/detok
bookkeeping — never attributed. This tool turns that gap into a measured
breakdown:

1. **Attribution pass** (pipeline_depth=1, engine.profile_sync=True): the
   engine's PhaseTimer splits each decode window's wall time into
   plan / upload / dispatch / device / fetch / commit, and the harness
   times detokenization of the emitted events — the full
   "plan/upload/device/fetch/commit/detok" split per window.
2. **Overlap pass** (pipeline_depth=2): the same workload through the
   overlapped pipeline; reports wall-time speedup, the pipeline occupancy
   counters (windows / overlapped / fallbacks / host syncs / plan
   uploads), and the host seconds that executed concurrently with device
   compute.
3. **Kernel + sampler attribution** (PR 18): each pass records which
   decode kernel served the device leg (`decode_kernel_tag`: ragged /
   gather / pp, "+fused" when the sampling tail ran in-program) and the
   one-dispatch-per-window invariant (`decode_dispatches`,
   `dispatches_per_window` — the unified ragged kernel keeps the common
   decode window at EXACTLY one device dispatch). The fused sampling
   tail never shows up in fetch/commit (it runs inside the window
   program), so its cost is split out standalone: `sampler_tail` times
   the fused vs unfused tail at the same [slots, vocab] geometry.

The record is appended (append-only, final name — tools/artifacts.py
policy, VERDICT r5 weak #7) to DECODE_PROFILE.jsonl at the repo root.
Optionally wraps the timed loops in a jax.profiler trace (--trace-dir)
for op-level drill-down in TensorBoard/XProf.

Usage:
    JAX_PLATFORMS=cpu python tools/decode_profile.py            # tiny, CPU
    python tools/decode_profile.py --model llama3-1b --slots 8 \
        --decode-steps 64 --windows 20 --trace-dir /tmp/xprof
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools.artifacts import append_jsonl  # noqa: E402

DEFAULT_OUT = os.path.join(REPO_ROOT, "DECODE_PROFILE.jsonl")


def build_engine(args, depth: int):
    import dataclasses

    from dynamo_tpu.engine.config import (
        EngineConfig, ModelConfig, get_model_config,
    )
    from dynamo_tpu.engine.engine import NativeEngine

    if args.model == "tiny-f32":
        mcfg = ModelConfig(dtype="float32", max_model_len=2048)
    else:
        mcfg = get_model_config(args.model)
    if args.quant:
        mcfg = dataclasses.replace(mcfg, quant=args.quant)
    ecfg = EngineConfig(
        page_size=args.page_size,
        num_pages=args.num_pages,
        max_slots=args.slots,
        max_prefill_chunk=512,
        max_model_len=min(mcfg.max_model_len, 2048),
        decode_steps=args.decode_steps,
        pipeline_depth=depth,
    )
    return NativeEngine(mcfg, ecfg, seed=0)


def run_pass(args, depth: int, profile_sync: bool, trace_dir=None) -> dict:
    """One measured decode run; returns phases + counters + wall time."""
    import jax

    from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams

    eng = build_engine(args, depth)
    max_tokens = args.windows * args.decode_steps
    # --sampled drives the fused-tail path (seeded, top_p = 1) so the
    # device leg carries the "+fused" kernel tag; default stays greedy
    # for comparability with pre-PR-18 records
    params = SamplingParams(
        max_tokens=max_tokens, ignore_eos=True,
        temperature=0.8 if args.sampled else 0.0,
        top_k=40 if args.sampled else 0,
        seed=1234 if args.sampled else 0)
    for i in range(args.slots):
        prompt = [(131 * i + j) % (eng.model_cfg.vocab_size - 1) + 1
                  for j in range(args.prompt_len)]
        eng.add_request(EngineRequest(f"p{i}", prompt, params))
    # warmup: prefill + two windows so every program is compiled before
    # the timed loop (first-use XLA compiles would swamp the phases)
    while eng.scheduler.waiting:
        eng.step()
    for _ in range(2):
        eng.step()
    eng.phases.reset()
    eng.profile_sync = profile_sync

    detok_buf = []
    tokens = 0
    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    while eng.has_work():
        events = eng.step()
        # the detokenize leg of the commit path: what llm/worker.py does
        # with each event before the bytes can leave the process
        with eng.phases.phase("detok"):
            for ev in events:
                if ev.token is not None:
                    detok_buf.append(f"<{ev.token}>")
                    tokens += 1
    wall = time.perf_counter() - t0
    if trace_dir:
        jax.profiler.stop_trace()

    return {
        "depth": depth,
        "profile_sync": profile_sync,
        "wall_s": round(wall, 4),
        "tokens": tokens,
        "tok_s": round(tokens / wall, 1) if wall else 0.0,
        "phases": eng.phases.split(),
        # which kernel served the device leg ("ragged"/"gather"/"pp",
        # "+fused" when the sampling tail ran inside the window program)
        "decode_kernel_tag": eng.decode_kernel_tag,
        "counters": {
            "decode_windows": eng.decode_windows,
            "decode_dispatches": eng.decode_dispatches,
            "pipeline_windows": eng.pipeline_windows,
            "pipeline_overlapped": eng.pipeline_overlapped,
            "pipeline_fallbacks": eng.pipeline_fallbacks,
            "decode_host_syncs": eng.decode_host_syncs,
            "decode_plan_uploads": eng.decode_plan_uploads,
        },
        # the PR-18 invariant: the common decode window is ONE dispatch
        "dispatches_per_window": round(
            eng.decode_dispatches / eng.decode_windows, 4)
        if eng.decode_windows else 0.0,
    }


def sampler_tail_split(args, vocab_size: int) -> dict:
    """Standalone fused-vs-unfused sampling-tail timing at the decode
    geometry [slots, vocab]. Inside a fused window the tail's cost rides
    the device leg (fetch/commit never see it), so attribution needs the
    tail measured on its own: `unfused_ms` is the full sort + double
    argsort + softmax-cumsum tail, `fused_ms` the single-argsort rank
    tail the common path dispatches (docs/PERF.md §3g)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine import sampler

    rng = np.random.default_rng(0)
    b = args.slots
    logits = jnp.asarray(rng.standard_normal((b, vocab_size)), jnp.float32)
    temp = jnp.full((b,), 0.8, jnp.float32)
    top_k = jnp.full((b,), 40, jnp.int32)
    top_p = jnp.ones((b,), jnp.float32)
    keys = sampler.make_keys(jnp.arange(b, dtype=jnp.int32),
                             jnp.zeros((b,), jnp.int32))

    fused_fn = jax.jit(sampler.sample_fused)
    unfused_fn = jax.jit(sampler.sample)

    def timed(fn, *a):
        fn(*a).block_until_ready()          # compile outside the clock
        reps = 20
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*a)
        out.block_until_ready()
        return (time.perf_counter() - t0) / reps * 1e3

    fused_ms = timed(fused_fn, logits, temp, top_k, keys)
    unfused_ms = timed(unfused_fn, logits, temp, top_k, top_p, keys)
    return {
        "batch": b,
        "vocab": vocab_size,
        "fused_ms": round(fused_ms, 4),
        "unfused_ms": round(unfused_ms, 4),
        "fused_over_unfused": round(fused_ms / unfused_ms, 4)
        if unfused_ms else 0.0,
    }


def run_stream_pass(args) -> dict:
    """Streamed long-context attribution (PERF.md §3h): one sequence
    whose context is ~4x the HBM page budget, driven through the
    tiered-KV streaming decode with profile_sync semantics (the stream
    loop is host-driven, so its phases are already synchronous). The
    PhaseTimer's `prefetch` phase isolates the double-buffer staging
    leg; the stream counters qualify it — a hit-dominated run means
    those seconds were ahead-of-consume copies, a late-dominated run
    means the tier is slower than the decode cadence and the staging
    time sat on the critical path."""
    from dynamo_tpu.engine.config import (
        EngineConfig, ModelConfig, get_model_config,
    )
    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.engine.scheduler import EngineRequest, SamplingParams
    from dynamo_tpu.engine.streaming import STREAM_STATS

    if args.model == "tiny-f32":
        mcfg = ModelConfig(dtype="float32", max_model_len=2048)
    else:
        mcfg = get_model_config(args.model)
    page = args.stream_page_size
    max_tokens = 8 * page
    total_pages = -(-(args.stream_prompt_len + max_tokens) // page)
    budget = max(total_pages // 4, 6)          # context = ~4x HBM budget
    ecfg = EngineConfig(
        page_size=page, num_pages=budget, max_slots=2,
        max_prefill_chunk=8 * page,
        prefill_buckets=(2 * page, 4 * page, 8 * page),
        max_model_len=mcfg.max_model_len,
        host_pages=2 * total_pages, stream_pages=4,
        stream_resident_pages=max(budget - 2, 4), stream_hot_pages=2)
    eng = NativeEngine(mcfg, ecfg, seed=0)
    prompt = [(7 * i + 3) % (mcfg.vocab_size - 1) + 1
              for i in range(args.stream_prompt_len)]
    eng.add_request(EngineRequest("stream", prompt, SamplingParams(
        max_tokens=max_tokens, temperature=0.0, ignore_eos=True)))
    s0 = STREAM_STATS.snapshot()
    eng.phases.reset()
    tokens = 0
    t0 = time.perf_counter()
    while eng.has_work():
        for ev in eng.step():
            if ev.token is not None:
                tokens += 1
    wall = time.perf_counter() - t0
    s1 = STREAM_STATS.snapshot()
    delta = {k: s1[k] - s0[k] for k in s1}
    hits, lates = delta["prefetch_hit"], delta["prefetch_late"]
    phases = eng.phases.split()
    return {
        "context_tokens": args.stream_prompt_len + max_tokens,
        "hbm_budget_pages": budget,
        "context_pages": total_pages,
        "wall_s": round(wall, 4),
        "tokens": tokens,
        "tok_s": round(tokens / wall, 1) if wall else 0.0,
        "phases": phases,
        "prefetch_s": round(
            phases.get("prefetch", {}).get("seconds", 0.0), 4),
        "stream_counters": delta,
        "prefetch_hit_ratio": round(hits / (hits + lates), 4)
        if hits + lates else 0.0,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default="tiny-f32",
                    help="registry name, or tiny-f32 (default: CPU-sized)")
    ap.add_argument("--quant", default="", help="'' or int8")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--decode-steps", type=int, default=8)
    ap.add_argument("--windows", type=int, default=12,
                    help="decode windows per request in the timed loop")
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--num-pages", type=int, default=512)
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="append-only JSONL artifact (final name)")
    ap.add_argument("--trace-dir", default=None,
                    help="also capture a jax.profiler trace here")
    ap.add_argument("--sampled", action="store_true",
                    help="seeded sampling (top_p=1): the fused-tail path")
    ap.add_argument("--no-stream", action="store_true",
                    help="skip the streamed long-context pass (PERF.md §3h)")
    ap.add_argument("--stream-prompt-len", type=int, default=320,
                    help="prompt length for the streamed pass (its HBM "
                         "budget is derived as ~1/4 of the context pages)")
    ap.add_argument("--stream-page-size", type=int, default=4,
                    help="page size for the streamed pass (small pages "
                         "keep the tiny-CPU stream geometry meaningful)")
    args = ap.parse_args(argv)

    import jax

    # 1. attribution: synchronous loop, device time isolated per phase
    attribution = run_pass(args, depth=1, profile_sync=True,
                           trace_dir=args.trace_dir)
    # 2. overlap: the pipelined loop on the same workload
    pipelined = run_pass(args, depth=2, profile_sync=False)
    # 3. the sampling tail, split out of the window program (PR 18)
    from dynamo_tpu.engine.config import ModelConfig, get_model_config
    vocab = (ModelConfig().vocab_size if args.model == "tiny-f32"
             else get_model_config(args.model).vocab_size)
    sampler_tail = sampler_tail_split(args, vocab)
    # 4. the streamed long-context leg: prefetch-phase attribution for
    # decode beyond the HBM page budget (PERF.md §3h)
    stream = None if args.no_stream else run_stream_pass(args)

    host_phases = ("plan", "upload", "commit", "detok")
    hidden_s = sum(pipelined["phases"].get(p, {}).get("seconds", 0.0)
                   for p in host_phases)
    c = pipelined["counters"]
    record = {
        "t": time.time(),
        "argv": vars(args),
        "jax": jax.__version__,
        "platform": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        "attribution": attribution,
        "pipelined": pipelined,
        "sampler_tail": sampler_tail,
        "stream": stream,
        "overlap": {
            # host seconds that executed while the device ran a window
            "host_s_overlapped_with_device": round(hidden_s, 4),
            "overlap_fraction": round(
                c["pipeline_overlapped"] / c["pipeline_windows"], 4)
            if c["pipeline_windows"] else 0.0,
            "speedup": round(
                attribution["wall_s"] / pipelined["wall_s"], 3)
            if pipelined["wall_s"] else 0.0,
        },
    }
    append_jsonl(args.out, record)
    print(json.dumps(record["overlap"]))
    print(f"appended record to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
