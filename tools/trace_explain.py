#!/usr/bin/env python
"""trace_explain: reconstruct one request's timeline from a trace file.

The answer to "explain this slow request": given a span JSONL file
(written by `TRACER.drain()` — one span per line, the schema in
dynamo_tpu/runtime/tracing.py), pick a trace and render

- the span TREE (parent links), offset + duration per span, attrs
  inline — frontend root, schedule, attempts, worker stream, remote
  prefill, queue wait, KV transfer;
- a summary: queue/admission wait, prefill legs, transfer bytes and
  per-fetch cost, per-window decode ITL (gaps between decode.emit
  instants), and the retry/migration story (attempt outcomes).

Usage:
    python tools/trace_explain.py TRACE.jsonl [--trace-id ID]
    python tools/trace_explain.py TRACE.jsonl --list
    python tools/trace_explain.py TRACE.jsonl --summary
    python tools/trace_explain.py TRACE.jsonl --chrome OUT.json

--summary aggregates the WHOLE file per span name — count, total time,
and p50/p95/p99 duration estimated through the bucketed Histogram
quantile estimator (observability/metrics.py Histogram.quantile, the
same estimator the SLO watchdog reads) — the cross-request view the
per-trace tree cannot give.

With no --trace-id the busiest non-scope trace is explained (scope:*
pseudo-traces — engine phases, router storms — are aggregate context,
not a request). --chrome re-exports the WHOLE file as a
chrome://tracing-loadable JSON via tools/artifacts.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def load_spans(path: str) -> List[dict]:
    spans = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "trace_id" in rec and "ts" in rec:
                spans.append(rec)
    return spans


def pick_trace(spans: List[dict]) -> Optional[str]:
    counts: Dict[str, int] = {}
    for s in spans:
        tid = s["trace_id"]
        if not tid.startswith("scope:"):
            counts[tid] = counts.get(tid, 0) + 1
    if not counts:
        return None
    return max(counts, key=lambda t: counts[t])


def _fmt_attrs(attrs: Optional[dict]) -> str:
    if not attrs:
        return ""
    return " " + " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))


def _percentile(vals: List[float], p: float) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    i = min(len(vals) - 1, int(round(p * (len(vals) - 1))))
    return vals[i]


def explain(spans: List[dict], trace_id: str) -> str:
    """Render one trace's timeline + summary as text (pure function —
    the tier-1 golden test drives it on the committed artifact)."""
    mine = [s for s in spans if s["trace_id"] == trace_id]
    if not mine:
        return f"trace {trace_id}: no spans"
    mine.sort(key=lambda s: (s["ts"], s["span_id"]))
    t_base = min(s["ts"] for s in mine)
    by_id = {s["span_id"]: s for s in mine}
    children: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in mine:
        parent = s.get("parent_id") or ""
        if parent and parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)

    out: List[str] = [f"trace {trace_id}: {len(mine)} span(s), "
                      f"{(max(x['ts'] + x['dur'] for x in mine) - t_base) * 1e3:.1f} ms end to end"]

    # defensive: a malformed file (e.g. span-id collisions from a
    # pre-fix process mix) could make the parent graph cyclic — render
    # each span at most once rather than recursing forever
    seen_ids: set = set()

    def render(s: dict, depth: int) -> None:
        if id(s) in seen_ids:
            return
        seen_ids.add(id(s))
        off = (s["ts"] - t_base) * 1e3
        dur = s["dur"] * 1e3
        mark = "!" if s.get("error") else ("·" if s["dur"] <= 0 else "—")
        out.append(f"  {off:9.2f}ms {'  ' * depth}{mark} {s['name']}"
                   + (f" [{dur:.2f}ms]" if s["dur"] > 0 else "")
                   + _fmt_attrs(s.get("attrs")))
        if depth < 64:
            for c in children.get(s["span_id"], ()):
                render(c, depth + 1)

    for r in roots:
        render(r, 0)
    for s in mine:              # orphans of a cyclic/malformed graph
        render(s, 0)

    # -- summary --------------------------------------------------------------
    def named(*names):
        return [s for s in mine if s["name"] in names]

    out.append("")
    out.append("summary:")
    waits = named("admission.wait", "queue.wait")
    if waits:
        total = sum(s["dur"] for s in waits) * 1e3
        out.append(f"  queue wait: {total:.2f} ms across {len(waits)} "
                   f"leg(s) ({', '.join(s['name'] for s in waits)})")
    sched = named("schedule", "router.schedule")
    if sched:
        out.append(f"  schedule: {sum(s['dur'] for s in sched) * 1e3:.2f} ms "
                   f"over {len(sched)} decision(s)")
    prefills = named("prefill.remote", "prefill.run")
    for s in prefills:
        out.append(f"  {s['name']}: {s['dur'] * 1e3:.2f} ms"
                   + _fmt_attrs(s.get("attrs")))
    xfers = named("kv.transfer", "kv.inject")
    if xfers:
        total_bytes = sum((s.get("attrs") or {}).get("bytes", 0)
                          for s in xfers)
        total_pages = sum((s.get("attrs") or {}).get("pages", 0)
                          for s in xfers)
        out.append(f"  kv transfer: {total_bytes} bytes / {total_pages} "
                   f"page(s) in {len(xfers)} leg(s), "
                   f"{sum(s['dur'] for s in xfers) * 1e3:.2f} ms")
    resumes = named("kv.transfer.resume")
    if resumes:
        pages = sum((s.get("attrs") or {}).get("committed_pages", 0)
                    for s in resumes)
        out.append(f"  kv transfer resumes: {len(resumes)} (continued "
                   f"past {pages} already-committed page(s))")
    salvages = named("kv.salvage")
    for s in salvages:
        a = s.get("attrs") or {}
        out.append(f"  kv salvage: kept {a.get('pages', '?')} committed "
                   f"page(s) ({a.get('tokens', '?')} tokens charged as "
                   "cached); only the tail re-prefilled locally")
    emits = sorted(named("decode.emit"), key=lambda s: s["ts"])
    if len(emits) >= 2:
        gaps = [(b["ts"] - a["ts"]) * 1e3
                for a, b in zip(emits, emits[1:])]
        out.append(f"  decode: {len(emits)} emit(s); itl p50 "
                   f"{_percentile(gaps, 0.5):.2f} ms, p95 "
                   f"{_percentile(gaps, 0.95):.2f} ms, max "
                   f"{max(gaps):.2f} ms")
    elif emits:
        out.append(f"  decode: {len(emits)} emit(s)")
    attempts = named("attempt")
    if attempts:
        outcomes: Dict[str, int] = {}
        for s in attempts:
            o = (s.get("attrs") or {}).get("outcome", "?")
            outcomes[o] = outcomes.get(o, 0) + 1
        story = ", ".join(f"{k}×{v}" for k, v in sorted(outcomes.items()))
        out.append(f"  attempts: {len(attempts)} ({story})")
    errs = [s for s in mine if s.get("error")]
    if errs:
        out.append(f"  errors: {len(errs)} span(s): "
                   + ", ".join(sorted({s['name'] for s in errs})))
    return "\n".join(out)


def summarize(spans: List[dict]) -> str:
    """Whole-file per-span-name latency table: count, total ms, and
    p50/p95/p99 from bucket counts (Histogram.quantile — the estimator
    is exact at bucket boundaries; +Inf-bucket ranks report the largest
    finite bound). Instants (dur <= 0) are counted but not timed."""
    from dynamo_tpu.observability.metrics import Histogram

    # span durations range from µs schedule decisions to multi-second
    # storms: a wide log-ish ladder keeps the estimator honest
    buckets = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
               0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
               float("inf"))
    hist = Histogram("trace_span_seconds", "span durations", ("name",),
                     buckets=buckets)
    totals: Dict[str, float] = {}
    instants: Dict[str, int] = {}
    for s in spans:
        name = s["name"]
        if s.get("dur", 0.0) > 0.0:
            hist.observe(name, value=s["dur"])
            totals[name] = totals.get(name, 0.0) + s["dur"]
        else:
            instants[name] = instants.get(name, 0) + 1
    out = [f"{len(spans)} span(s), "
           f"{len(set(s['trace_id'] for s in spans))} trace(s)"]
    out.append(f"  {'span':<28}{'count':>7}{'total ms':>11}"
               f"{'p50 ms':>9}{'p95 ms':>9}{'p99 ms':>9}")
    for name in sorted(totals, key=lambda n: -totals[n]):
        n = hist.count(name)
        p50, p95, p99 = (hist.quantile(q, name) * 1e3
                         for q in (0.50, 0.95, 0.99))
        out.append(f"  {name:<28}{n:>7}{totals[name] * 1e3:>11.2f}"
                   f"{p50:>9.3f}{p95:>9.3f}{p99:>9.3f}")
    for name in sorted(instants):
        if name not in totals:
            out.append(f"  {name:<28}{instants[name]:>7}"
                       f"{'instant':>11}")
    table = link_estimator_table(spans)
    if table:
        out.append("")
        out.extend(table)
    stream_table = stream_frontier_table(spans)
    if stream_table:
        out.append("")
        out.extend(stream_table)
    return "\n".join(out)


def stream_frontier_table(spans: List[dict]) -> List[str]:
    """Per-(shard, host) stream table from kv.transfer.stream spans
    (sharded parallel transfer, disagg/remote_transfer.py): wall time,
    bytes, and resumes per stream, plus the MIN-FRONTIER STALL — how
    long the slowest stream of each transfer outlived the fastest
    (the time the request-wide min frontier, which gates early decode
    and bounds salvage, sat waiting on the straggler). The straggler
    column names the stream that pinned the min. Empty when no span
    carries a stream id (pre-ISSUE-15 artifacts render unchanged)."""
    # (trace_id, request_id) -> stream spans of that transfer
    by_xfer: Dict[tuple, List[dict]] = {}
    for s in spans:
        attrs = s.get("attrs") or {}
        if s["name"] == "kv.transfer.stream" and s.get("dur", 0.0) > 0.0:
            key = (s["trace_id"], attrs.get("request_id", "?"))
            by_xfer.setdefault(key, []).append(s)
    if not by_xfer:
        return []
    per_stream: Dict[str, dict] = {}
    stalls: List[float] = []
    stragglers: Dict[str, int] = {}
    for rows in by_xfer.values():
        ends = [(r["ts"] + r["dur"], r) for r in rows]
        if len(ends) >= 2:
            last_end, last = max(ends, key=lambda x: x[0])
            first_end = min(e for e, _ in ends)
            stalls.append(last_end - first_end)
            a = last.get("attrs") or {}
            skey = f"{a.get('engine_id', '?')}/{a.get('host', '?')}" \
                   f"#{a.get('stream', '?')}"
            stragglers[skey] = stragglers.get(skey, 0) + 1
        for r in rows:
            a = r.get("attrs") or {}
            skey = f"{a.get('engine_id', '?')}/{a.get('host', '?')}" \
                   f"#{a.get('stream', '?')}"
            row = per_stream.setdefault(
                skey, {"n": 0, "bytes": 0, "dur": 0.0, "resumes": 0})
            row["n"] += 1
            row["bytes"] += a.get("bytes") or 0
            row["dur"] += r["dur"]
            row["resumes"] += a.get("resumes") or 0
    out = ["kv transfer streams (per shard, host):",
           f"  {'stream':<24}{'sends':>6}{'bytes':>12}{'total ms':>10}"
           f"{'resumes':>8}{'straggler':>10}"]
    for skey in sorted(per_stream):
        row = per_stream[skey]
        out.append(f"  {skey:<24}{row['n']:>6}{row['bytes']:>12}"
                   f"{row['dur'] * 1e3:>10.2f}{row['resumes']:>8}"
                   f"{stragglers.get(skey, 0):>10}")
    if stalls:
        stalls.sort()
        out.append(
            f"  min-frontier stall (slowest-fastest stream end): "
            f"p50 {stalls[len(stalls) // 2] * 1e3:.2f} ms, "
            f"max {stalls[-1] * 1e3:.2f} ms over {len(stalls)} "
            "parallel transfer(s)")
    return out


def link_estimator_table(spans: List[dict]) -> List[str]:
    """Per-link estimated-vs-actual transfer-time table from kv.transfer
    spans carrying the sender's pre-send `est_s` attr (the
    TransferCostModel's answer at dispatch time). The diagnosis surface
    for routing regressions caused by a stale bandwidth EWMA: a link
    whose err% goes strongly negative is being under-estimated (the
    EWMA believes it faster than it is) and the transfer-aware router
    is over-routing onto it. Empty when no span carries an estimate
    (pre-ISSUE-11 artifacts render unchanged)."""
    links: Dict[str, List[dict]] = {}
    for s in spans:
        attrs = s.get("attrs") or {}
        if s["name"] == "kv.transfer" and "est_s" in attrs \
                and s.get("dur", 0.0) > 0.0:
            links.setdefault(attrs.get("engine_id", "?"), []).append(s)
    if not links:
        return []
    out = ["kv transfer estimator (per link, est vs actual):",
           f"  {'link':<24}{'sends':>6}{'bytes':>12}{'est ms':>9}"
           f"{'act ms':>9}{'err %':>8}{'cold':>6}"]
    for link in sorted(links):
        rows = links[link]
        est = sum((r["attrs"].get("est_s") or 0.0) for r in rows)
        act = sum(r["dur"] for r in rows)
        nbytes = sum((r["attrs"].get("bytes") or 0) for r in rows)
        cold = sum(1 for r in rows if r["attrs"].get("est_cold"))
        err = (est - act) / act * 100 if act else 0.0
        out.append(f"  {link:<24}{len(rows):>6}{nbytes:>12}"
                   f"{est * 1e3:>9.2f}{act * 1e3:>9.2f}{err:>8.1f}"
                   f"{cold:>6}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_explain", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("trace_file", help="span JSONL (TRACER.drain records)")
    ap.add_argument("--trace-id", help="trace to explain "
                                       "(default: busiest request trace)")
    ap.add_argument("--list", action="store_true",
                    help="list trace ids with span counts and exit")
    ap.add_argument("--summary", action="store_true",
                    help="whole-file per-span-name latency table "
                         "(p50/p95/p99 via Histogram.quantile) and exit")
    ap.add_argument("--chrome", metavar="OUT_JSON",
                    help="also write the whole file as a chrome://tracing "
                         "JSON (tools/artifacts.py policy)")
    args = ap.parse_args(argv)

    spans = load_spans(args.trace_file)
    if not spans:
        print(f"no spans in {args.trace_file}", file=sys.stderr)
        return 1
    if args.list:
        counts: Dict[str, int] = {}
        for s in spans:
            counts[s["trace_id"]] = counts.get(s["trace_id"], 0) + 1
        for tid, n in sorted(counts.items(), key=lambda kv: -kv[1]):
            print(f"{n:6d}  {tid}")
        return 0
    if args.summary:
        print(summarize(spans))
        return 0
    if args.chrome:
        from dynamo_tpu.runtime.tracing import chrome_trace

        from tools.artifacts import write_json
        write_json(args.chrome, chrome_trace(spans), overwrite=True)
        print(f"chrome trace -> {args.chrome}", file=sys.stderr)
    tid = args.trace_id or pick_trace(spans)
    if tid is None:
        print("no request traces in file (only scope:* spans); pass "
              "--trace-id to explain one of those", file=sys.stderr)
        return 1
    print(explain(spans, tid))
    return 0


if __name__ == "__main__":
    sys.exit(main())
