"""Canonical disaggregated serving graph (SDK), the reference's L6 role.

Reference equivalent: examples/llm/graphs/disagg_router.py:16-22 — the
Frontend -> Processor -> Router -> VllmWorker -> PrefillWorker chain. Here
the Processor and Router roles live inside the Frontend process: the model
watcher builds the preprocess -> KV-router -> worker pipeline per registered
model (dynamo_tpu/frontend/discovery.py), which is the same split the
reference's standalone http binary uses (components/http/src/main.rs).

Services:
- Frontend        OpenAI HTTP + model discovery + KV-aware routing
- DecodeWorker    DisaggDecodeWorker + KvTransferServer (NIXL-server role)
                  + model registration
- PrefillWorker   queue consumer + RemoteTransferBackend (NIXL-client role)

Run (CPU demo, one command):
  python -m dynamo_tpu.sdk.serve examples.disagg.graph:Frontend \
      -f examples/disagg/config.cpu.yaml --start-control-plane

then:
  curl -N localhost:8099/v1/chat/completions -H 'Content-Type: application/json' \
    -d '{"model": "tiny", "stream": true, "max_tokens": 16, \
         "messages": [{"role": "user", "content": "hello"}]}'

`config.yaml` carries the reference's canonical values (llama3-8b-class
model, KV block 64, max_model_len 16384 — examples/llm/configs/
disagg_router.yaml) for a real TPU deployment.
"""
from __future__ import annotations

from dynamo_tpu.disagg import (
    DisaggDecodeWorker, DisaggregatedRouter, KvTransferServer, PrefillQueue,
    RemoteTransferBackend, ShardedKvTransferGroup,
)
from dynamo_tpu.disagg import PrefillWorker as QueuePrefillWorker
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import NativeEngine
from dynamo_tpu.frontend.discovery import register_model
from dynamo_tpu.frontend.serve import run_frontend
from dynamo_tpu.llm.worker import NativeEngineWorker, serve_llm_worker
from dynamo_tpu.run import build_card
from dynamo_tpu.sdk import async_on_start, depends, service
from dynamo_tpu.sdk.config import ServiceConfig

NS = "dynamo-demo"


def _build(cfg: dict):
    """Model card + engine from one service's config section."""
    card = build_card(cfg.get("model", "tiny"))
    model_cfg = card.model_config()
    max_len = int(cfg.get("max_model_len",
                          min(card.context_length, model_cfg.max_model_len)))
    engine = NativeEngine(
        model_cfg,
        EngineConfig(
            page_size=int(cfg.get("page_size", 64)),  # reference KV block 64
            num_pages=int(cfg.get("num_pages", 128)),
            max_slots=int(cfg.get("max_slots", 4)),
            max_prefill_chunk=int(cfg.get("max_prefill_chunk", 512)),
            prefill_buckets=tuple(
                cfg.get("prefill_buckets", (16, 64, 256, 512))),
            max_model_len=max_len,
        ),
        eos_token_ids=set(card.eos_token_ids))
    return card, engine


@service(name="PrefillWorker", namespace=NS, component="prefill")
class PrefillWorker:
    """Prefill engine consuming the durable queue; ships KV pages to the
    decode workers over the remote transfer plane."""

    @async_on_start
    async def boot(self):
        cfg = ServiceConfig.global_instance().for_service("PrefillWorker")
        card, engine = _build(cfg)
        queue = PrefillQueue(self.runtime.messaging, NS, card.name)
        transfer = RemoteTransferBackend(self.runtime.kv)
        self.worker = await QueuePrefillWorker(
            NativeEngineWorker(engine), queue, transfer,
            self.runtime.messaging,
            max_inflight=int(cfg.get("max_inflight", 4))).start()


@service(name="DecodeWorker", namespace=NS, component="backend")
class DecodeWorker:
    """Decode engine with conditional remote prefill + KV-injection server."""

    prefill = depends(PrefillWorker)  # start-order edge; coupled via queue

    @async_on_start
    async def boot(self):
        cfg = ServiceConfig.global_instance().for_service("DecodeWorker")
        card, engine = _build(cfg)
        queue = PrefillQueue(self.runtime.messaging, NS, card.name)
        router = DisaggregatedRouter(
            # reference example values: threshold 10, queue gate 2
            # (examples/llm/configs/disagg_router.yaml:38-40)
            max_local_prefill_length=int(
                cfg.get("max_local_prefill_length", 10)),
            max_prefill_queue_size=int(
                cfg.get("max_prefill_queue_size", 2)),
            model=card.name)
        router.start_watching(self.runtime.kv)
        worker = DisaggDecodeWorker(
            engine, self.runtime.messaging, router, queue,
            worker_id=f"decode-{self.runtime.worker_id}",
            prefill_timeout_s=float(cfg.get("prefill_timeout_s", 120.0)))
        await worker.start()
        # sharded parallel transfer (PERF.md §3f): transfer_hosts > 1
        # runs per-host endpoints with one chunk-committed stream per
        # (cache shard, host) — on a real multi-host decode mesh each
        # host runs its own endpoint so aggregate transfer bandwidth
        # scales with host count; transfer_streams optionally overrides
        # the natural shard count (must divide num_kv_heads)
        hosts = int(cfg.get("transfer_hosts", 1))
        if hosts > 1:
            self.kv_server = await ShardedKvTransferGroup(
                worker, worker.engine_id, hosts=hosts,
                n_streams=int(cfg.get("transfer_streams", 0))).start()
        else:
            self.kv_server = await KvTransferServer(
                worker, worker.engine_id).start()
        await self.kv_server.register(self.runtime.kv, self.runtime.lease.id)
        await serve_llm_worker(self.runtime, NS, "backend", worker,
                               card=card)
        await register_model(self.runtime.kv, card.name, NS, "backend", card)
        self.worker = worker


@service(name="Frontend", namespace=NS, component="frontend")
class Frontend:
    """OpenAI HTTP frontend; Processor+Router roles run in-process via the
    model watcher's discovery-built pipeline."""

    decode = depends(DecodeWorker)  # start-order edge

    @async_on_start
    async def boot(self):
        cfg = ServiceConfig.global_instance().for_service("Frontend")
        self.http = await run_frontend(
            self.runtime, port=int(cfg.get("port", 8099)),
            kv_routing=bool(cfg.get("kv_routing", True)))
        print(f"FRONTEND http=:{self.http.port}", flush=True)
