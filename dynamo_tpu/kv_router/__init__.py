from dynamo_tpu.kv_router.indexer import KvIndexer, KvIndexerSharded, RadixTree
from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent, KvCacheRemoveData, KvCacheStoreData, KvCacheStoredBlockData,
    RouterEvent, tokens_hash,
)
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.kv_router.scheduler import (
    DefaultWorkerSelector, KvScheduler, TransferAwareSelector,
)
from dynamo_tpu.kv_router.scoring import ProcessedEndpoints, WorkerMetrics

__all__ = [
    "KvIndexer", "KvIndexerSharded", "RadixTree", "KvCacheEvent",
    "KvCacheRemoveData", "KvCacheStoreData", "KvCacheStoredBlockData",
    "RouterEvent", "tokens_hash", "KvRouter", "DefaultWorkerSelector",
    "TransferAwareSelector", "KvScheduler", "ProcessedEndpoints",
    "WorkerMetrics",
]
