"""KV event + metrics wire protocol for the KV-aware router.

Mirrors the reference's event protocol (reference:
lib/llm/src/kv_router/protocols.rs:42-121): a worker's block allocator emits
`RouterEvent{worker_id, KvCacheEvent}` onto the event plane subject
`{ns}.{component}.kv_events`; Stored events carry the parent chained hash plus
per-block (chained block_hash, content-only tokens_hash) pairs, Removed events
carry chained block hashes. Two hash kinds, as in the reference
(indexer.rs:87-135):

- **tokens_hash** (LocalBlockHash): xxh3_64(seed 1337) over the page's token
  bytes only — computable by a router from query tokens alone; keys the radix
  tree.
- **block_hash** (ExternalSequenceBlockHash): the chained sequence hash the
  worker's allocator assigned (engine/kv_cache.py page_hash) — unique per
  prefix, keys the per-worker O(1) lookup used to apply Removed events.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from dynamo_tpu.engine.kv_cache import tokens_hash

__all__ = [
    "tokens_hash", "compute_page_hashes", "KvCacheStoredBlockData",
    "KvCacheStoreData", "KvCacheRemoveData", "KvCacheEvent", "RouterEvent",
    "POOL_SOURCE_PREFIX", "pool_source_id", "is_pool_source",
    "pool_source_worker",
]

# Cluster-wide shared KV pool (engine/kv_pool.py): pool Stored/Removed
# events ride this same plane under a `pool:{worker_id}` source id — the
# radix tree then indexes pool-resident prefixes NEXT TO worker-resident
# ones, and the router splits the two at schedule time (a pool: score is
# a *fetchable* prefix, not a resident one). The id embeds the SOURCE
# worker so the watch-driven eviction that purges a dead worker also
# purges its pool-source entries — the selector must never price a
# fetch from a corpse (docs/PERF.md §3e).
POOL_SOURCE_PREFIX = "pool:"


def pool_source_id(worker_id: str) -> str:
    return f"{POOL_SOURCE_PREFIX}{worker_id}"


def is_pool_source(worker_id: str) -> bool:
    return worker_id.startswith(POOL_SOURCE_PREFIX)


def pool_source_worker(worker_id: str) -> str:
    """The source worker behind a pool: id (identity for plain ids)."""
    return worker_id[len(POOL_SOURCE_PREFIX):] \
        if is_pool_source(worker_id) else worker_id


def compute_page_hashes(tokens: Sequence[int], page_size: int) -> List[int]:
    """tokens_hash of each *full* page of the sequence (router query side)."""
    n_full = len(tokens) // page_size
    return [tokens_hash(tokens[i * page_size:(i + 1) * page_size])
            for i in range(n_full)]


@dataclasses.dataclass
class KvCacheStoredBlockData:
    block_hash: int    # chained sequence hash (worker-assigned)
    tokens_hash: int   # content-only hash (router-computable)


@dataclasses.dataclass
class KvCacheStoreData:
    parent_hash: Optional[int]  # chained hash of the preceding block, None=root
    blocks: List[KvCacheStoredBlockData] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class KvCacheRemoveData:
    block_hashes: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class KvCacheEvent:
    event_id: int
    data: "KvCacheStoreData | KvCacheRemoveData"


@dataclasses.dataclass
class RouterEvent:
    worker_id: str
    event: KvCacheEvent
    # publish-time unix timestamp (time.time()). Optional for wire
    # compat; when present the router derives its event-plane LAG
    # (now - ts at apply time), which drives the stale-snapshot
    # degraded mode and the llm_cp_event_lag_seconds gauge.
    ts: Optional[float] = None

    def pack(self) -> dict:
        d = self.event.data
        if isinstance(d, KvCacheStoreData):
            data = {"kind": "stored", "parent_hash": d.parent_hash,
                    "blocks": [[b.block_hash, b.tokens_hash] for b in d.blocks]}
        else:
            data = {"kind": "removed", "block_hashes": list(d.block_hashes)}
        out = {"worker_id": self.worker_id,
               "event_id": self.event.event_id, "data": data}
        if self.ts is not None:
            out["ts"] = self.ts
        return out

    @classmethod
    def unpack(cls, msg: dict) -> "RouterEvent":
        d = msg["data"]
        if d["kind"] == "stored":
            data = KvCacheStoreData(
                parent_hash=d.get("parent_hash"),
                blocks=[KvCacheStoredBlockData(b[0], b[1]) for b in d["blocks"]])
        else:
            data = KvCacheRemoveData(block_hashes=list(d["block_hashes"]))
        return cls(worker_id=msg["worker_id"],
                   event=KvCacheEvent(event_id=msg["event_id"], data=data),
                   ts=msg.get("ts"))
