"""Standalone KV-aware router service.

Role of the reference's `components/router` binary (reference:
components/router/src/main.rs): a dedicated process that maintains the
radix index + load snapshot for a worker fleet and answers routing queries
over the request plane, so frontends/processors that don't embed a router
can call `route` as a service. The response carries the chosen worker_id
plus the overlap evidence, and the caller then uses Client.direct() to hit
that worker (same contract as the reference's processor flow, SURVEY.md
§3.2).

Run: python -m dynamo_tpu.kv_router.main \
        --coordinator 127.0.0.1:6230 --namespace ns --component worker \
        [--router-component router] [--block-size 64]
"""
from __future__ import annotations

import argparse
import asyncio
import logging

from dynamo_tpu.kv_router.router import KvRouter

log = logging.getLogger("dynamo_tpu.router_main")


class RouterService:
    """Serves `route` queries backed by a KvRouter over a worker fleet."""

    def __init__(self, runtime, namespace: str, worker_component: str,
                 block_size: int, router_component: str = "router",
                 endpoint: str = "generate"):
        self.runtime = runtime
        self.namespace = namespace
        self.block_size = block_size
        self._worker_comp = runtime.namespace(namespace).component(
            worker_component)
        self._router_comp = runtime.namespace(namespace).component(
            router_component)
        self._client = None
        self.router: KvRouter = None
        self._endpoint_name = endpoint
        self._served = None

    async def start(self) -> "RouterService":
        self._client = self._worker_comp.endpoint(
            self._endpoint_name).client()
        await self._client.start()
        # events ride the WORKER component's kv_events subject
        self.router = KvRouter(self._worker_comp, self._client,
                               self.block_size, publish_hit_events=True)
        await self.router.start()
        self._served = await self._router_comp.endpoint("route").serve(
            self._route)
        return self

    async def stop(self) -> None:
        if self.router is not None:
            await self.router.stop()
        if self._client is not None:
            await self._client.stop()

    async def _route(self, request, context):
        tokens = list(request.get("token_ids", ()))
        if not tokens:
            yield {"error": "token_ids required"}
            return
        overlap = self.router.find_matches_for_tokens(tokens)
        try:
            # KvRouter.schedule also drains + publishes kv-hit-rate events
            # (publish_hit_events=True) — one implementation of that loop
            worker_id = await self.router.schedule(tokens)
        except Exception as e:  # no live workers etc.
            yield {"error": f"{type(e).__name__}: {e}"}
            return
        best = max(overlap.scores.values(), default=0)
        yield {"worker_id": worker_id,
               "overlap_blocks": int(overlap.scores.get(worker_id, 0)),
               "best_overlap_blocks": int(best)}


async def _amain(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    host, port = args.coordinator.rsplit(":", 1)
    runtime = await DistributedRuntime.connect(host, int(port),
                                               "kv-router")
    svc = RouterService(runtime, args.namespace, args.component,
                        block_size=args.block_size,
                        router_component=args.router_component,
                        endpoint=args.endpoint)
    await svc.start()
    log.info("router serving %s/%s/route over %s/%s", args.namespace,
             args.router_component, args.namespace, args.component)
    print("READY router", flush=True)
    await asyncio.Event().wait()


def main() -> None:
    # layered defaults <- DYN_CONFIG file <- DYN_* env <- CLI flags
    # (utils/settings.py; e.g. DYN_ROUTER__BLOCK_SIZE=128)
    from dynamo_tpu.utils.settings import load_settings
    s = load_settings({"router": {
        "coordinator": "127.0.0.1:6230", "block_size": 64}}).router
    ap = argparse.ArgumentParser(description="dynamo-tpu standalone router")
    ap.add_argument("--coordinator", default=s.coordinator)
    ap.add_argument("--namespace", required=True)
    ap.add_argument("--component", required=True,
                    help="worker component to route over")
    ap.add_argument("--router-component", default="router")
    ap.add_argument("--endpoint", default="generate")
    ap.add_argument("--block-size", type=int, default=s.block_size)
    args = ap.parse_args()
    from dynamo_tpu.utils.logconfig import configure_logging
    configure_logging()
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
