"""Process-local router scoring counters (/metrics: llm_router_*).

Same pattern as runtime/cpstats.py CP_STATS: plain numbers bumped on the
scoring path, folded into Prometheus gauges at /metrics render time by
frontend/service.py and observability/exporter.py. The source is the
transfer-aware worker selector (kv_router/scheduler.py
TransferAwareSelector): every schedule decision records whether the
transfer-cost term was live, cold-fallback (a candidate link had no
bandwidth EWMA yet), or frozen (stale-snapshot degraded mode pinned the
last-good costs), plus the winner's estimated transfer seconds and the
fleet's estimator-error EWMA — the signals that make a routing
regression caused by a stale or missing bandwidth EWMA diagnosable from
a scrape (docs/OBSERVABILITY.md §9, docs/PERF.md routing section).
"""
from __future__ import annotations


class RouterScoringStats:
    FIELDS = (
        "transfer_scored",       # decisions scored with the transfer term
        "cold_scored",           # decisions where >=1 candidate was cold
        "frozen_scored",         # decisions under the degraded cost freeze
        "last_transfer_est_s",   # winner's estimated transfer seconds
        "last_transfer_bytes",   # winner's bytes-to-move estimate
        "est_err_abs_frac",      # fleet mean |estimator error| (EWMA-fed)
        # cluster-pool scoring (engine/kv_pool.py, docs/PERF.md §3e)
        "pool_scored",           # decisions with a fetchable pool prefix
        "last_pool_fetch_blocks",  # winner's pool-fetchable block count
        # fail-slow health fold (runtime/health.py, docs/RESILIENCE.md
        # "Fail-slow failure model"): decisions where a candidate's
        # health score was below 1.0, and the winner's own score —
        # a degraded-but-alive worker shedding load is visible here
        # before any breaker trips
        "health_scored",         # decisions with >=1 degraded candidate
        "last_pick_health",      # winner's health score (1.0 = healthy)
    )

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        for name in self.FIELDS:
            setattr(self, name, 0)

    def snapshot(self) -> dict:
        return {name: getattr(self, name) for name in self.FIELDS}


ROUTER_STATS = RouterScoringStats()
