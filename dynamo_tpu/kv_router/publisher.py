"""Worker-side KV event + load-metrics publishing.

Reference: lib/llm/src/kv_router/publisher.rs:33-137 — the engine worker
pushes block Stored/Removed events onto the event plane subject
`{ns}.{component}.kv_events` and exposes its latest ForwardPassMetrics via
the endpoint stats handler, which the router-side aggregator scrapes
(metrics_aggregator.rs:26-145). Here the event source is our own allocator
(engine/kv_cache.py PageAllocator.drain_events) instead of a patched vLLM.
"""
from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Dict, Optional

from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent, KvCacheRemoveData, KvCacheStoreData, KvCacheStoredBlockData,
    RouterEvent,
)
from dynamo_tpu.kv_router.scoring import ProcessedEndpoints, WorkerMetrics

log = logging.getLogger("dynamo_tpu.kv_router")

KV_EVENTS_SUBJECT = "kv_events"
KV_HIT_RATE_SUBJECT = "kv-hit-rate"


class KvEventPublisher:
    """Converts allocator events into RouterEvents on the event plane."""

    def __init__(self, component, worker_id: str):
        self.component = component
        self.worker_id = worker_id
        self._event_id = 0

    async def publish_allocator_events(self, events) -> int:
        """Publish a batch of (kind, page, seq_hash, parent, tokens_hash)
        tuples drained from PageAllocator; returns the number of RouterEvents
        published. Consecutive stored events that chain (parent == previous
        seq_hash) coalesce into one multi-block Stored event, and runs of
        removals into one Removed event, so an N-page prefill costs O(1)
        event-plane messages (the reference batches the same way —
        KvCacheStoreData carries a block list)."""
        batches: list = []
        for kind, _pid, seq_hash, parent, tok_hash in events:
            if kind == "stored":
                prev = batches[-1] if batches else None
                if (prev is not None and isinstance(prev, KvCacheStoreData)
                        and prev.blocks and prev.blocks[-1].block_hash == parent):
                    prev.blocks.append(KvCacheStoredBlockData(seq_hash, tok_hash))
                else:
                    batches.append(KvCacheStoreData(
                        parent_hash=parent or None,
                        blocks=[KvCacheStoredBlockData(seq_hash, tok_hash)]))
            else:
                prev = batches[-1] if batches else None
                if isinstance(prev, KvCacheRemoveData):
                    prev.block_hashes.append(seq_hash)
                else:
                    batches.append(KvCacheRemoveData(block_hashes=[seq_hash]))
        for data in batches:
            ev = RouterEvent(self.worker_id,
                             KvCacheEvent(self._event_id, data),
                             ts=time.time())
            self._event_id += 1
            await self.component.publish(KV_EVENTS_SUBJECT, ev.pack())
        return len(batches)

    async def publish_stored(self, parent_hash: Optional[int], blocks) -> None:
        data = KvCacheStoreData(
            parent_hash=parent_hash,
            blocks=[KvCacheStoredBlockData(bh, th) for bh, th in blocks])
        ev = RouterEvent(self.worker_id, KvCacheEvent(self._event_id, data),
                         ts=time.time())
        self._event_id += 1
        await self.component.publish(KV_EVENTS_SUBJECT, ev.pack())

    async def publish_removed(self, block_hashes) -> None:
        ev = RouterEvent(self.worker_id, KvCacheEvent(
            self._event_id, KvCacheRemoveData(list(block_hashes))),
            ts=time.time())
        self._event_id += 1
        await self.component.publish(KV_EVENTS_SUBJECT, ev.pack())


class KvMetricsPublisher:
    """Holds the worker's latest load snapshot; plugs into the endpoint's
    stats handler so the aggregator's scrape sees it."""

    def __init__(self):
        self.metrics = WorkerMetrics()

    def update(self, m) -> None:
        if dataclasses.is_dataclass(m) and not isinstance(m, WorkerMetrics):
            m = WorkerMetrics.from_dict(dataclasses.asdict(m))
        self.metrics = m

    def stats_handler(self) -> dict:
        return dataclasses.asdict(self.metrics)


class KvMetricsAggregator:
    """Router-side scrape loop: polls live workers' stats handlers into a
    ProcessedEndpoints snapshot (reference metrics_aggregator.rs:26-145)."""

    def __init__(self, client, interval_s: float = 0.5):
        self.client = client            # runtime Client on the worker endpoint
        self.interval_s = interval_s
        self.endpoints = ProcessedEndpoints()
        self._task: Optional[asyncio.Task] = None
        self._listeners = []
        # pristine last successful scrape per worker — carry-forward copies
        # come from here, NOT from the bump-mutated working snapshot, so
        # optimistic bumps never compound across scrape windows
        self._last_scraped: Dict[str, WorkerMetrics] = {}

    def on_update(self, cb) -> None:
        """cb(ProcessedEndpoints, removed_worker_ids) per scrape."""
        self._listeners.append(cb)

    async def scrape_once(self) -> ProcessedEndpoints:
        stats = await self.client.scrape_stats()
        workers: Dict[str, WorkerMetrics] = {}
        for worker_id, payload in stats.items():
            try:
                m = WorkerMetrics.from_dict(payload)
            except (TypeError, KeyError):
                continue
            workers[worker_id] = m
            self._last_scraped[worker_id] = dataclasses.replace(m)
        # a live instance that failed this scrape resumes from its last
        # *pristine* snapshot (not the bump-mutated working copy); one that
        # never published stats is still routable, with unit totals so the
        # scheduler's optimistic bump has teeth (zero totals would make it
        # look permanently idle and attract the whole request stream between
        # scrapes). Either way a live instance must never count as removed —
        # removal purges its radix-index entries. DRAINING instances are
        # deliberately NOT carried forward (instance_ids excludes them):
        # they fall into `removed`, which fences their index entries and
        # drops them from scheduling until they come back ready.
        # (getattr: scrape-only client doubles in tests lack the
        # lifecycle-aware instance_ids surface)
        list_ids = getattr(self.client, "instance_ids", None)
        live = list_ids() if list_ids is not None else self.client.instances
        for worker_id in set(live) - set(workers):
            last = self._last_scraped.get(worker_id)
            workers[worker_id] = (dataclasses.replace(last)
                                  if last is not None else WorkerMetrics(
                                      request_total_slots=1, kv_total_blocks=1))
        removed = set(self.endpoints.workers) - set(workers)
        for worker_id in removed:
            self._last_scraped.pop(worker_id, None)
        self.endpoints = ProcessedEndpoints(workers)
        for cb in self._listeners:
            cb(self.endpoints, removed)
        return self.endpoints

    async def start(self) -> None:
        async def loop():
            # dynalint: backoff-ok=fixed-interval scrape; a failed cycle is logged and the next tick retries at the same cadence (no reconnect amplification: scrape fan-out is bounded by the fleet)
            while True:
                try:
                    await self.scrape_once()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("metrics scrape failed")
                await asyncio.sleep(self.interval_s)
        self._task = asyncio.create_task(loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            self._task = None
