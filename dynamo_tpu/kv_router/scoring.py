"""Aggregated per-worker load state consumed by the KV scheduler.

Mirrors the reference's ProcessedEndpoints (reference:
lib/llm/src/kv_router/scoring.rs:24-53): the live worker set with each
worker's latest ForwardPassMetrics, plus load average/stddev over active
blocks used to normalize the cost function.
"""
from __future__ import annotations

import dataclasses
import statistics
from typing import Dict, List


@dataclasses.dataclass
class WorkerMetrics:
    """Field-for-field the reference's ForwardPassMetrics
    (reference: lib/llm/src/kv_router/protocols.rs:42-54); published by the
    engine worker (engine/scheduler.py EngineMetrics is the source)."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0
    # decode-window occupancy (ours, beyond the reference's set; VERDICT
    # r3 weak #3): cumulative device (step, slot) pairs run in decode
    # windows and the post-finish tail among them
    window_slot_steps: int = 0
    window_wasted_steps: int = 0
    # speculative decoding (engine/spec.py): acceptance = accepted/proposed
    spec_proposed_tokens: int = 0
    spec_accepted_tokens: int = 0
    # overlapped decode pipeline occupancy (engine pipelined loop,
    # docs/PERF.md): dispatched windows / committed via the pipeline /
    # committed while a follow-up ran on device / reconciliation
    # fallbacks / blocking fetches / fresh host plan stagings
    decode_windows: int = 0
    pipeline_windows: int = 0
    pipeline_overlapped: int = 0
    pipeline_fallbacks: int = 0
    decode_host_syncs: int = 0
    decode_plan_uploads: int = 0
    # mixed prefill+decode steps (docs/PERF.md): fused steps run, and
    # decode stall steps (steps where running streams emitted nothing
    # because the step carried no decode rows — ~0 with mixed steps on)
    mixed_steps: int = 0
    decode_stall_steps: int = 0
    # KV representation (ops/kv_quant.py): HBM bytes per page, quant bit
    # width (0 = unquantized), cumulative wire-representation transfer
    # volume (quantized bytes on kv_quant engines)
    kv_page_bytes: int = 0
    kv_quant_bits: int = 0
    kv_transfer_bytes: int = 0
    kv_transfer_fetches: int = 0
    # chunk-committed streaming (disagg/remote_transfer.py): resumed
    # transfers, salvaged committed-prefix pages, epoch-fenced stale
    # chunks, per-IO timeouts treated as link death
    kv_transfer_resumes: int = 0
    kv_transfer_salvaged_pages: int = 0
    kv_transfer_stale_chunks: int = 0
    kv_transfer_link_timeouts: int = 0
    # per-step ledger figures (observability/ledger.py): steps,
    # recompile events, EWMA tok/s, MFU estimate, padding-waste
    # fraction, and offload tier occupancy (fleet rollup inputs)
    engine_steps: int = 0
    engine_recompiles: int = 0
    engine_tok_s: float = 0.0
    engine_mfu: float = 0.0
    engine_pad_frac: float = 0.0
    kv_host_pages_used: int = 0
    kv_host_pages_total: int = 0
    kv_disk_pages_used: int = 0
    kv_disk_pages_total: int = 0
    # tiered-KV streaming decode (engine/streaming.py): streamed steps,
    # double-buffer prefetch outcomes, spill / quarantine page counts
    # and prefetch-stalled steps (0s on engines without stream_pages)
    kv_stream_steps: int = 0
    kv_stream_prefetch_hit: int = 0
    kv_stream_prefetch_late: int = 0
    kv_stream_pages_spilled: int = 0
    kv_stream_pages_quarantined: int = 0
    kv_stream_stall_steps: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "WorkerMetrics":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})


@dataclasses.dataclass
class ProcessedEndpoints:
    workers: Dict[str, WorkerMetrics] = dataclasses.field(default_factory=dict)

    @property
    def worker_ids(self) -> List[str]:
        return sorted(self.workers)

    @property
    def load_avg(self) -> float:
        if not self.workers:
            return 0.0
        return statistics.fmean(
            w.kv_active_blocks for w in self.workers.values())

    @property
    def load_std(self) -> float:
        if len(self.workers) < 2:
            return 0.0
        return statistics.pstdev(
            w.kv_active_blocks for w in self.workers.values())
