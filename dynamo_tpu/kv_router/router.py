"""KvRouter facade: event subscription + radix index + scheduler in one.

Reference: lib/llm/src/kv_router/kv_router.rs:51-164 — subscribes to the
component's `kv_events` subject, feeds the indexer, keeps a metrics-driven
worker snapshot, and answers `schedule(tokens) -> worker_id`. Dead workers
(instance key deleted) are purged from both the index and the endpoint
snapshot, matching the reference's remove_worker path (indexer.rs:380-387).
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional, Sequence

from dynamo_tpu.kv_router.indexer import KvIndexer, MatchResult
from dynamo_tpu.kv_router.protocols import (
    RouterEvent, compute_page_hashes, is_pool_source, pool_source_id,
    pool_source_worker,
)
from dynamo_tpu.kv_router.publisher import (
    KV_EVENTS_SUBJECT, KV_HIT_RATE_SUBJECT, KvMetricsAggregator,
)
from dynamo_tpu.kv_router.scheduler import KvScheduler, WorkerSelector
from dynamo_tpu.observability.serving import SERVING
from dynamo_tpu.runtime.backoff import Backoff
from dynamo_tpu.runtime.cpstats import CP_STATS

log = logging.getLogger("dynamo_tpu.kv_router")


class KvRouter:
    def __init__(self, component, worker_client, block_size: int,
                 selector: Optional[WorkerSelector] = None,
                 scrape_interval_s: float = 0.5,
                 publish_hit_events: bool = False,
                 degraded_lag_s: float = 2.0,
                 degraded_backlog: int = 10_000,
                 degraded_min_s: float = 1.0,
                 event_batch: int = 2048,
                 pool_membership=None):
        """degraded_lag_s / degraded_backlog: thresholds for the
        STALE-SNAPSHOT DEGRADED MODE. Prefix scores are advisory — when
        the event plane lags (publish ts → apply time) past
        degraded_lag_s, or the event backlog passes degraded_backlog,
        the router keeps scheduling on its last-good prefix scores +
        load metrics and REPORTS the staleness (self.degraded,
        llm_cp_router_degraded) instead of blocking requests behind
        event application. Exit uses half-threshold hysteresis plus a
        degraded_min_s dwell so the gaps BETWEEN a lag storm's delayed
        bursts can't flap the flag.

        pool_membership: the cross-host pool's ring membership view
        (runtime/placement.py PoolMembership) — when wired, pool-host
        instance events (pool-host:{host} ids) feed it at watch-event
        time and `_split_pool_scores` stops pricing pool fetches the
        moment no live member can serve them."""
        self.component = component
        self.client = worker_client
        self.block_size = block_size
        self.indexer = KvIndexer(block_size)
        if selector is None:
            # serving default: transfer-aware scoring over the process-
            # global TransferCostModel (observability/fleet.py). With no
            # measured links every candidate prices at the same default
            # prior, so a fresh router ranks exactly like the prefix-
            # only selector until transfer samples arrive.
            from dynamo_tpu.kv_router.scheduler import TransferAwareSelector
            selector = TransferAwareSelector()
        self.scheduler = KvScheduler(block_size, selector)
        self.aggregator = KvMetricsAggregator(worker_client, scrape_interval_s)
        self.publish_hit_events = publish_hit_events
        self.degraded_lag_s = degraded_lag_s
        self.degraded_backlog = degraded_backlog
        self.degraded_min_s = degraded_min_s
        self.event_batch = event_batch
        self.pool_membership = pool_membership
        self.degraded = False
        self.degraded_entries = 0
        self._degraded_since = 0.0
        self.event_lag_s = 0.0
        self.events_applied = 0
        self._event_task: Optional[asyncio.Task] = None

    async def start(self) -> "KvRouter":
        stream = await self.component.subscribe(KV_EVENTS_SUBJECT)
        self._event_task = asyncio.create_task(self._event_pump(stream))

        def on_metrics(endpoints, removed):
            # fence re-check: a scrape that RACED a death can still carry
            # the dead worker (it answered $STATS just before its key
            # vanished), and update_endpoints swaps the whole snapshot —
            # without this filter the corpse re-enters scheduling until
            # the next scrape. The client's watch state is authoritative.
            instances = getattr(self.client, "instances", None)
            if instances is not None:
                for worker_id in [w for w in endpoints.workers
                                  if w not in instances]:
                    del endpoints.workers[worker_id]
            self.scheduler.update_endpoints(endpoints)
            for worker_id in removed:
                self.indexer.remove_worker(worker_id)
            for worker_id in endpoints.workers:
                self.indexer.revive_worker(worker_id)
                # a restarted worker's POOL publishes must not stay
                # tombstoned behind its old generation's eviction
                self.indexer.revive_worker(pool_source_id(worker_id))

        self.aggregator.on_update(on_metrics)

        def on_instance(kind, worker_id, info):
            # watch-event-time eviction: the moment discovery drops an
            # instance (deregistration or lease expiry) its cached-prefix
            # scores and endpoint entry go — NOT at the next metrics
            # scrape. Before this, a dead worker's radix-index overlap
            # kept out-scoring live workers for every warm prefix, so
            # each such stream burned one failed dispatch on the corpse
            # until the circuit breaker tripped.
            from dynamo_tpu.runtime.component import (
                STATUS_DRAINING, instance_status,
            )
            from dynamo_tpu.runtime.placement import is_pool_host_instance
            if is_pool_host_instance(worker_id):
                # pool-HOST liveness (ring membership): a pool host's
                # instance delete leaves the ring AT EVENT TIME — the
                # ownership epoch bumps and _split_pool_scores stops
                # pricing fetches no live member can serve, the same
                # corpse-routing fence the worker delete below applies
                # to pool SOURCES
                if self.pool_membership is not None:
                    self.pool_membership.on_instance(kind, worker_id, info)
                return
            if kind == "delete":
                self.indexer.remove_worker(worker_id)
                # pool-source twin (mirror of the PR 4 eviction above):
                # the dead worker's SHARED-POOL publishes go with it at
                # watch-event time, so the transfer-aware selector never
                # prices a pool fetch sourced from a corpse — without
                # this, a warm shared prefix kept scoring as fetchable
                # until the next full resync
                self.indexer.remove_worker(pool_source_id(worker_id))
                self.scheduler.remove_worker(worker_id)
                # fail-slow twin of the same eviction: a dead worker's
                # latency evidence and SLOW flag must not bias a reused
                # instance name (frontend/reliability.py evicts its
                # breaker state through its own listener)
                from dynamo_tpu.runtime.health import HEALTH
                HEALTH.forget(worker_id)
            elif kind == "put" \
                    and instance_status(info) == STATUS_DRAINING:
                # drain fence: keep the worker out of prefix scoring so
                # cached-overlap can't pull new streams onto it; its
                # in-flight streams keep running untouched
                self.indexer.remove_worker(worker_id)

        if hasattr(self.client, "add_listener"):
            self.client.add_listener(on_instance)
        await self.aggregator.start()
        return self

    async def _event_pump(self, stream) -> None:
        """Event-plane consumer with backpressure accounting.

        Events apply in per-tick batches bounded by event_batch, with a
        yield between batches so schedule() calls interleave instead of
        starving behind a storm. Lag = now - newest applied event's
        publish ts; an idle tick (no events, empty backlog) means the
        pump is caught up, so lag resets. The pump survives stream death
        the same way the watch pumps do: bounded backoff + resubscribe
        (prefix state needs no resync — the instance watch evicts dead
        workers, and missed Stored events only cost routing optimality)."""
        backoff = Backoff(base_s=0.05, max_s=2.0, stable_reset_s=10.0)
        idle_s = 0.25
        while True:
            try:
                batch = await stream.next_batch(self.event_batch,
                                                timeout=idle_s)
                now = time.time()
                for _subj, msg in batch:
                    try:
                        ev = RouterEvent.unpack(msg)
                        self.indexer.apply_event(ev)
                        self.events_applied += 1
                        if ev.ts is not None:
                            self.event_lag_s = max(0.0, now - ev.ts)
                    except Exception:
                        log.exception("bad kv event: %r", msg)
                backlog = stream.depth()
                if not batch and backlog == 0:
                    self.event_lag_s = 0.0   # caught up and idle
                self._update_degraded(backlog)
                backoff.reset()
                if batch:
                    await asyncio.sleep(0)   # let schedule() interleave
            except asyncio.CancelledError:
                raise
            except Exception:
                log.warning("kv event stream failed; resubscribing",
                            exc_info=True)
                try:
                    await stream.aclose()
                except Exception:  # dynalint: swallow-ok=old-stream-best-effort-close
                    pass
                await backoff.sleep()
                try:
                    stream = await self.component.subscribe(
                        KV_EVENTS_SUBJECT)
                except Exception:
                    log.warning("kv event resubscribe failed",
                                exc_info=True)

    def _update_degraded(self, backlog: int) -> None:
        lag = self.event_lag_s
        if not self.degraded:
            if lag > self.degraded_lag_s or backlog > self.degraded_backlog:
                self.degraded = True
                self.degraded_entries += 1
                self._degraded_since = time.monotonic()
                log.warning(
                    "kv_router entering stale-snapshot degraded mode "
                    "(event lag %.2fs, backlog %d): scheduling continues "
                    "on last-good prefix scores + load", lag, backlog)
        elif lag < self.degraded_lag_s / 2 \
                and backlog < self.degraded_backlog / 2 \
                and time.monotonic() - self._degraded_since \
                >= self.degraded_min_s:
            self.degraded = False
            log.info("kv_router exited degraded mode (event lag %.2fs, "
                     "backlog %d)", lag, backlog)
        # degraded interaction with transfer-aware scoring: while the
        # snapshot is stale, the cost term FREEZES at its last-good
        # per-worker values rather than recomputing from stale load/
        # backlog signals — degradation must not amplify staleness
        freeze = getattr(self.scheduler.selector, "freeze_cost", None)
        if freeze is not None:
            freeze(self.degraded)
        CP_STATS.event_lag_seconds = lag
        CP_STATS.event_backlog = backlog
        CP_STATS.router_degraded = int(self.degraded)
        CP_STATS.router_degraded_entries = self.degraded_entries

    async def stop(self) -> None:
        if self._event_task:
            self._event_task.cancel()
            self._event_task = None
        await self.aggregator.stop()

    # -- scheduling ----------------------------------------------------------

    @property
    def last_score_components(self) -> dict:
        """Per-worker score components of the LAST schedule decision
        (transfer-aware selectors only; {} otherwise) — the diagnosis
        surface for "why did it route there"."""
        return getattr(self.scheduler.selector, "last_components", {})

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> MatchResult:
        return self.indexer.find_matches(
            compute_page_hashes(tokens, self.block_size))

    def _split_pool_scores(self, overlap: MatchResult) -> int:
        """Strip `pool:{worker}` entries out of the match scores and fold
        them into ONE fetchable-prefix depth (the deepest live-sourced
        pool match). Pool scores are not resident overlap — a candidate
        must FETCH those pages — so they must never rank a worker as if
        it held them; the selector prices the fetch instead. The watch
        eviction purges dead pool sources at event time; the instance
        re-check here is the same authoritative-watch fence the metrics
        path uses (a racing Stored event could re-add a corpse's edge
        between eviction and this schedule).

        Pool-HOST liveness rides the same fence one layer down: with a
        cross-host pool, the bytes live on ring-member pool hosts, not
        with the publishing workers — when membership is wired and NO
        live host remains, every pool score is unfetchable regardless
        of source liveness, so pricing zeroes at watch-event time
        instead of burning a doomed fetch ladder per schedule. (With
        any member left, replication R keeps entries fetchable, so a
        single host death changes nothing here — the fetch-side replica
        walk fails over.)"""
        pool_matched = 0
        dead_pool = (self.pool_membership is not None
                     and not self.pool_membership.live_hosts())
        instances = getattr(self.client, "instances", None)
        for wid in [w for w in overlap.scores if is_pool_source(w)]:
            score = overlap.scores.pop(wid)
            if dead_pool:
                continue   # no live pool host can serve ANY fetch
            src = pool_source_worker(wid)
            if instances is not None and src not in instances:
                continue   # corpse-sourced: never price a fetch from it
            pool_matched = max(pool_matched, score)
        return pool_matched

    async def schedule(self, tokens: Sequence[int],
                       exclude=(), qos: str = "") -> str:
        """Pick the best worker for this token sequence; returns worker_id.
        `exclude`: instances currently ejected (circuit breaker open) —
        dropped from scoring unless that would leave no candidates.
        DRAINING instances join the exclusion the same way (planned
        maintenance takes no new assignments). `qos`: the request's
        QoS class (runtime/qos.py) — its latency weight scales the
        transfer-aware selector's cost term, steering interactive
        requests around backlogged links first."""
        t0 = time.monotonic()
        draining = getattr(self.client, "draining_ids", None)
        if draining is not None:
            drains = draining()
            if drains:
                exclude = set(exclude) | set(drains)
        overlap = self.find_matches_for_tokens(tokens)
        pool_matched = self._split_pool_scores(overlap)
        from dynamo_tpu.runtime.qos import DEFAULT_POLICY
        qos_cls = DEFAULT_POLICY.resolve(qos or None)
        worker_id = self.scheduler.schedule(len(tokens), overlap,
                                            exclude=exclude,
                                            pool_matched=pool_matched,
                                            qos=qos_cls.name,
                                            qos_weight=qos_cls
                                            .latency_weight)
        # serving-path histogram (llm_schedule_seconds): observed HERE,
        # at the real scheduling decision, so the frontend's kv-routed
        # path and a bare router (cluster_sim) account identically; the
        # reliability layer's fallback pick observes only when no
        # router is wired
        SERVING.schedule.observe(value=time.monotonic() - t0)
        if self.publish_hit_events:
            for ev in self.scheduler.drain_hit_events():
                await self.component.publish(KV_HIT_RATE_SUBJECT, {
                    "worker_id": ev.worker_id, "isl_blocks": ev.isl_blocks,
                    "overlap_blocks": ev.overlap_blocks})
        else:
            self.scheduler.drain_hit_events()
        return worker_id
