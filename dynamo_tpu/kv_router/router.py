"""KvRouter facade: event subscription + radix index + scheduler in one.

Reference: lib/llm/src/kv_router/kv_router.rs:51-164 — subscribes to the
component's `kv_events` subject, feeds the indexer, keeps a metrics-driven
worker snapshot, and answers `schedule(tokens) -> worker_id`. Dead workers
(instance key deleted) are purged from both the index and the endpoint
snapshot, matching the reference's remove_worker path (indexer.rs:380-387).
"""
from __future__ import annotations

import asyncio
import logging
from typing import Optional, Sequence

from dynamo_tpu.kv_router.indexer import KvIndexer, MatchResult
from dynamo_tpu.kv_router.protocols import RouterEvent, compute_page_hashes
from dynamo_tpu.kv_router.publisher import (
    KV_EVENTS_SUBJECT, KV_HIT_RATE_SUBJECT, KvMetricsAggregator,
)
from dynamo_tpu.kv_router.scheduler import KvScheduler, WorkerSelector

log = logging.getLogger("dynamo_tpu.kv_router")


class KvRouter:
    def __init__(self, component, worker_client, block_size: int,
                 selector: Optional[WorkerSelector] = None,
                 scrape_interval_s: float = 0.5,
                 publish_hit_events: bool = False):
        self.component = component
        self.client = worker_client
        self.block_size = block_size
        self.indexer = KvIndexer(block_size)
        self.scheduler = KvScheduler(block_size, selector)
        self.aggregator = KvMetricsAggregator(worker_client, scrape_interval_s)
        self.publish_hit_events = publish_hit_events
        self._event_task: Optional[asyncio.Task] = None

    async def start(self) -> "KvRouter":
        sub = await self.component.subscribe(KV_EVENTS_SUBJECT)

        async def pump():
            async for _subj, msg in sub:
                try:
                    self.indexer.apply_event(RouterEvent.unpack(msg))
                except Exception:
                    log.exception("bad kv event: %r", msg)

        self._event_task = asyncio.create_task(pump())

        def on_metrics(endpoints, removed):
            self.scheduler.update_endpoints(endpoints)
            for worker_id in removed:
                self.indexer.remove_worker(worker_id)
            for worker_id in endpoints.workers:
                self.indexer.revive_worker(worker_id)

        self.aggregator.on_update(on_metrics)

        def on_instance(kind, worker_id, info):
            # watch-event-time eviction: the moment discovery drops an
            # instance (deregistration or lease expiry) its cached-prefix
            # scores and endpoint entry go — NOT at the next metrics
            # scrape. Before this, a dead worker's radix-index overlap
            # kept out-scoring live workers for every warm prefix, so
            # each such stream burned one failed dispatch on the corpse
            # until the circuit breaker tripped.
            from dynamo_tpu.runtime.component import (
                STATUS_DRAINING, instance_status,
            )
            if kind == "delete":
                self.indexer.remove_worker(worker_id)
                self.scheduler.remove_worker(worker_id)
            elif kind == "put" \
                    and instance_status(info) == STATUS_DRAINING:
                # drain fence: keep the worker out of prefix scoring so
                # cached-overlap can't pull new streams onto it; its
                # in-flight streams keep running untouched
                self.indexer.remove_worker(worker_id)

        if hasattr(self.client, "add_listener"):
            self.client.add_listener(on_instance)
        await self.aggregator.start()
        return self

    async def stop(self) -> None:
        if self._event_task:
            self._event_task.cancel()
            self._event_task = None
        await self.aggregator.stop()

    # -- scheduling ----------------------------------------------------------

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> MatchResult:
        return self.indexer.find_matches(
            compute_page_hashes(tokens, self.block_size))

    async def schedule(self, tokens: Sequence[int],
                       exclude=()) -> str:
        """Pick the best worker for this token sequence; returns worker_id.
        `exclude`: instances currently ejected (circuit breaker open) —
        dropped from scoring unless that would leave no candidates.
        DRAINING instances join the exclusion the same way (planned
        maintenance takes no new assignments)."""
        draining = getattr(self.client, "draining_ids", None)
        if draining is not None:
            drains = draining()
            if drains:
                exclude = set(exclude) | set(drains)
        overlap = self.find_matches_for_tokens(tokens)
        worker_id = self.scheduler.schedule(len(tokens), overlap,
                                            exclude=exclude)
        if self.publish_hit_events:
            for ev in self.scheduler.drain_hit_events():
                await self.component.publish(KV_HIT_RATE_SUBJECT, {
                    "worker_id": ev.worker_id, "isl_blocks": ev.isl_blocks,
                    "overlap_blocks": ev.overlap_blocks})
        else:
            self.scheduler.drain_hit_events()
        return worker_id
