"""Global KV-cache radix-tree index for KV-aware routing.

Re-implements the reference's indexer semantics (reference:
lib/llm/src/kv_router/indexer.rs:163-900) TPU-side: a prefix tree whose edges
are content-only page hashes (tokens_hash), each node recording which workers
hold that page. `find_matches` walks a query's page-hash prefix accumulating
per-worker overlap counts; `apply_event` applies worker Stored/Removed events
using a per-worker `block_hash -> node` map for O(1) application;
`remove_worker` purges a dead worker's pages (driven by the client watch on
instance keys, matching indexer.rs:380-387).

The reference runs the tree in a single owner thread with mpsc channels; here
the tree is a plain object owned by the asyncio event loop (single-threaded by
construction), and `KvIndexer` is the event-plane-fed wrapper. A hash-sharded
variant (`KvIndexerSharded`, reference indexer.rs:677-900) splits workers
across independent trees to bound per-tree size.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence

import xxhash

from dynamo_tpu.kv_router.protocols import (
    KvCacheRemoveData, KvCacheStoreData, RouterEvent, compute_page_hashes,
)


@dataclasses.dataclass
class MatchResult:
    """Per-worker count of query prefix pages resident on that worker."""

    scores: Dict[str, int] = dataclasses.field(default_factory=dict)
    # frequency of recent use of the matched prefix (when tracking enabled)
    frequencies: List[int] = dataclasses.field(default_factory=list)

    def best(self) -> Optional[str]:
        if not self.scores:
            return None
        return max(self.scores, key=lambda w: self.scores[w])


class _Node:
    __slots__ = ("tokens_hash", "parent", "children", "workers", "recent_uses")

    def __init__(self, tokens_hash: int, parent: Optional["_Node"]):
        self.tokens_hash = tokens_hash
        self.parent = parent
        self.children: Dict[int, _Node] = {}
        # worker_id -> block_hash this worker stored the page under
        self.workers: Dict[str, int] = {}
        self.recent_uses: Deque[float] = deque()


class RadixTree:
    def __init__(self, expiration_duration_s: Optional[float] = None):
        self.root = _Node(0, None)
        # worker_id -> {block_hash -> node}
        self.lookup: Dict[str, Dict[int, _Node]] = {}
        self.expiration_s = expiration_duration_s

    # -- matching ------------------------------------------------------------

    def find_matches(self, page_hashes: Sequence[int],
                     early_exit: bool = False,
                     now: Optional[float] = None) -> MatchResult:
        """Walk the query's page-hash prefix, accumulating per-worker overlap.

        A worker's score is the number of leading query pages it holds
        (reference indexer.rs:239-275 walks exactly this way: the walk stops
        at the first page no worker holds).
        """
        result = MatchResult()
        node = self.root
        for h in page_hashes:
            nxt = node.children.get(h)
            if nxt is None:
                break
            node = nxt
            for worker in node.workers:
                result.scores[worker] = result.scores.get(worker, 0) + 1
            if self.expiration_s is not None:
                t = now if now is not None else time.monotonic()
                self._expire(node, t)
                node.recent_uses.append(t)
                result.frequencies.append(len(node.recent_uses))
            if early_exit and len(node.workers) == 1:
                break
        return result

    def _expire(self, node: _Node, now: float) -> None:
        cutoff = now - self.expiration_s
        while node.recent_uses and node.recent_uses[0] < cutoff:
            node.recent_uses.popleft()

    # -- event application ---------------------------------------------------

    def apply_event(self, event: RouterEvent) -> None:
        worker = event.worker_id
        data = event.event.data
        table = self.lookup.setdefault(worker, {})
        if isinstance(data, KvCacheStoreData):
            if data.parent_hash is None or data.parent_hash == 0:
                node = self.root
            else:
                node = table.get(data.parent_hash)
                if node is None:
                    # parent unknown (events raced a router restart): drop the
                    # event — root-attaching a mid-sequence page would forge a
                    # depth-1 prefix edge and cause false routing matches
                    return
            for blk in data.blocks:
                child = node.children.get(blk.tokens_hash)
                if child is None:
                    child = _Node(blk.tokens_hash, node)
                    node.children[blk.tokens_hash] = child
                # re-store under a new block_hash: drop the stale mapping
                # (invariant: table entries are {bh: node.workers[w]==bh})
                old = child.workers.get(worker)
                if old is not None and old != blk.block_hash:
                    table.pop(old, None)
                child.workers[worker] = blk.block_hash
                table[blk.block_hash] = child
                node = child
        elif isinstance(data, KvCacheRemoveData):
            for bh in data.block_hashes:
                node = table.pop(bh, None)
                if node is None:
                    continue
                if node.workers.get(worker) == bh:
                    del node.workers[worker]
                self._maybe_prune(node)

    def _maybe_prune(self, node: _Node) -> None:
        while (node.parent is not None and not node.workers
               and not node.children):
            parent = node.parent
            if parent.children.get(node.tokens_hash) is node:
                del parent.children[node.tokens_hash]
            node = parent

    def remove_worker(self, worker: str) -> None:
        table = self.lookup.pop(worker, None)
        if not table:
            return
        for node in set(table.values()):
            node.workers.pop(worker, None)
            self._maybe_prune(node)

    def clear_all_blocks(self, worker: str) -> None:
        """Worker restarted with an empty cache: drop its pages, keep it known."""
        self.remove_worker(worker)
        self.lookup[worker] = {}

    # -- introspection -------------------------------------------------------

    def num_nodes(self) -> int:
        count = 0
        stack = [self.root]
        while stack:
            n = stack.pop()
            count += 1
            stack.extend(n.children.values())
        return count - 1  # exclude root

    def worker_block_count(self, worker: str) -> int:
        return len(self.lookup.get(worker, {}))


class KvIndexer:
    """Event-fed index: subscribe to `{ns}.{component}.kv_events` and answer
    overlap queries (reference indexer.rs:499-668)."""

    def __init__(self, block_size: int,
                 expiration_duration_s: Optional[float] = None,
                 native: object = "auto"):
        self.block_size = block_size
        # native C++ tree (dynamo_tpu/native/kv_indexer.cpp) when available;
        # the Python tree is the fallback and the frequency-tracking path
        self.tree = None
        if native and expiration_duration_s is None:
            try:  # lazy: native.radix imports MatchResult from this module
                from dynamo_tpu.native import radix
                if radix.available():
                    self.tree = radix.NativeRadixTree()
            except Exception:
                if native is True:
                    raise
        if self.tree is None:
            if native is True:
                raise RuntimeError("native kv indexer requested but "
                                   "unavailable")
            self.tree = RadixTree(expiration_duration_s)
        self.events_applied = 0
        # tombstones: in-flight events from a removed worker must not
        # resurrect it (they'd leak ghost nodes forever, since a worker
        # absent from the endpoint snapshot can never be removed again)
        self._removed: set = set()

    def apply_event(self, event: RouterEvent) -> None:
        if event.worker_id in self._removed:
            return
        self.tree.apply_event(event)
        self.events_applied += 1

    def revive_worker(self, worker: str) -> None:
        """A worker id re-appeared live (restart): accept its events again."""
        self._removed.discard(worker)

    def apply_raw(self, msg: dict) -> None:
        self.apply_event(RouterEvent.unpack(msg))

    def find_matches(self, page_hashes: Sequence[int]) -> MatchResult:
        return self.tree.find_matches(page_hashes)

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> MatchResult:
        return self.find_matches(
            compute_page_hashes(tokens, self.block_size))

    def remove_worker(self, worker: str) -> None:
        self._removed.add(worker)
        self.tree.remove_worker(worker)


class KvIndexerSharded:
    """Shards workers across independent trees (reference indexer.rs:677-900).

    Queries fan out to every shard and merge; events touch exactly one shard,
    so application parallelizes across owner tasks in a multi-loop deployment.
    """

    def __init__(self, block_size: int, num_shards: int = 4,
                 expiration_duration_s: Optional[float] = None):
        self.block_size = block_size
        self.shards = [KvIndexer(block_size, expiration_duration_s)
                       for _ in range(num_shards)]

    def _shard_for(self, worker: str) -> KvIndexer:
        # stable across processes/restarts — Python hash() is salted per
        # process (PYTHONHASHSEED), which would scatter a worker's events
        # across different shards after a restart (VERDICT r2 weak #6)
        h = xxhash.xxh3_64(worker.encode("utf-8"), seed=1337).intdigest()
        return self.shards[h % len(self.shards)]

    def apply_event(self, event: RouterEvent) -> None:
        self._shard_for(event.worker_id).apply_event(event)

    def find_matches(self, page_hashes: Sequence[int]) -> MatchResult:
        merged = MatchResult()
        for shard in self.shards:
            res = shard.find_matches(page_hashes)
            merged.scores.update(res.scores)
            # per-depth use counts sum across shards (each shard tracks its
            # own matched path; total recent uses of depth i is the sum)
            for i, f in enumerate(res.frequencies):
                if i < len(merged.frequencies):
                    merged.frequencies[i] += f
                else:
                    merged.frequencies.append(f)
        return merged

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> MatchResult:
        return self.find_matches(compute_page_hashes(tokens, self.block_size))

    def remove_worker(self, worker: str) -> None:
        self._shard_for(worker).remove_worker(worker)

    def revive_worker(self, worker: str) -> None:
        self._shard_for(worker).revive_worker(worker)
