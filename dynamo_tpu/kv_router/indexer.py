"""Global KV-cache radix-tree index for KV-aware routing.

Re-implements the reference's indexer semantics (reference:
lib/llm/src/kv_router/indexer.rs:163-900) TPU-side: a prefix tree whose edges
are content-only page hashes (tokens_hash), each node recording which workers
hold that page. `find_matches` walks a query's page-hash prefix accumulating
per-worker overlap counts; `apply_event` applies worker Stored/Removed events
using a per-worker `block_hash -> node` map for O(1) application;
`remove_worker` purges a dead worker's pages (driven by the client watch on
instance keys, matching indexer.rs:380-387).

The reference runs the tree in a single owner thread with mpsc channels; here
the tree is a plain object owned by the asyncio event loop (single-threaded by
construction), and `KvIndexer` is the event-plane-fed wrapper. A hash-sharded
variant (`KvIndexerSharded`, reference indexer.rs:677-900) splits workers
across independent trees to bound per-tree size.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import xxhash

from dynamo_tpu.kv_router.protocols import (
    KvCacheRemoveData, KvCacheStoreData, RouterEvent, compute_page_hashes,
)
from dynamo_tpu.runtime.cpstats import CP_STATS

# incremental-eviction budgets: a dead 100k-node worker must not stall
# find_matches for the whole purge. remove_worker() processes one
# EVICT_CHUNK synchronously (small workers behave exactly as before);
# the rest drains EVICT_AMORTIZE nodes per subsequent apply_event /
# find_matches call, so eviction cost is amortized across the very
# traffic that needs the tree responsive.
EVICT_CHUNK = 512
EVICT_AMORTIZE = 64


@dataclasses.dataclass
class MatchResult:
    """Per-worker count of query prefix pages resident on that worker."""

    scores: Dict[str, int] = dataclasses.field(default_factory=dict)
    # frequency of recent use of the matched prefix (when tracking enabled)
    frequencies: List[int] = dataclasses.field(default_factory=list)

    def best(self) -> Optional[str]:
        if not self.scores:
            return None
        return max(self.scores, key=lambda w: self.scores[w])


class _Node:
    __slots__ = ("tokens_hash", "parent", "children", "workers", "recent_uses")

    def __init__(self, tokens_hash: int, parent: Optional["_Node"]):
        self.tokens_hash = tokens_hash
        self.parent = parent
        self.children: Dict[int, _Node] = {}
        # worker_id -> block_hash this worker stored the page under
        self.workers: Dict[str, int] = {}
        self.recent_uses: Deque[float] = deque()


class RadixTree:
    def __init__(self, expiration_duration_s: Optional[float] = None):
        self.root = _Node(0, None)
        # worker_id -> {block_hash -> node}
        self.lookup: Dict[str, Dict[int, _Node]] = {}
        self.expiration_s = expiration_duration_s
        self.node_count = 0
        # incremental eviction state: worker -> pending (block_hash, node)
        # pairs still holding that worker's entries. While a worker is
        # here, find_matches filters it from scores — the tree answers as
        # if the purge already finished, the WORK is what's amortized.
        self._evicting: Dict[str, Deque[Tuple[int, "_Node"]]] = {}

    # -- matching ------------------------------------------------------------

    def find_matches(self, page_hashes: Sequence[int],
                     early_exit: bool = False,
                     now: Optional[float] = None) -> MatchResult:
        """Walk the query's page-hash prefix, accumulating per-worker overlap.

        A worker's score is the number of leading query pages it holds
        (reference indexer.rs:239-275 walks exactly this way: the walk stops
        at the first page no worker holds).
        """
        if self._evicting:
            self.process_evictions(EVICT_AMORTIZE)
        result = MatchResult()
        node = self.root
        for h in page_hashes:
            nxt = node.children.get(h)
            if nxt is None:
                break
            node = nxt
            for worker in node.workers:
                result.scores[worker] = result.scores.get(worker, 0) + 1
            if self.expiration_s is not None:
                t = now if now is not None else time.monotonic()
                self._expire(node, t)
                node.recent_uses.append(t)
                result.frequencies.append(len(node.recent_uses))
            if early_exit and len(node.workers) == 1:
                break
        if self._evicting:
            # a mid-eviction worker's leftover entries must not score:
            # the router would route onto the corpse the purge exists
            # to remove (this filter is what makes chunked eviction
            # OBSERVABLY identical to the old synchronous purge)
            for worker in self._evicting:
                result.scores.pop(worker, None)
        return result

    def _expire(self, node: _Node, now: float) -> None:
        cutoff = now - self.expiration_s
        while node.recent_uses and node.recent_uses[0] < cutoff:
            node.recent_uses.popleft()

    # -- event application ---------------------------------------------------

    def apply_event(self, event: RouterEvent) -> None:
        if self._evicting:
            self.process_evictions(EVICT_AMORTIZE)
        worker = event.worker_id
        data = event.event.data
        table = self.lookup.setdefault(worker, {})
        if isinstance(data, KvCacheStoreData):
            if data.parent_hash is None or data.parent_hash == 0:
                node = self.root
            else:
                node = table.get(data.parent_hash)
                if node is None:
                    # parent unknown (events raced a router restart): drop the
                    # event — root-attaching a mid-sequence page would forge a
                    # depth-1 prefix edge and cause false routing matches
                    return
            for blk in data.blocks:
                child = node.children.get(blk.tokens_hash)
                if child is None:
                    child = _Node(blk.tokens_hash, node)
                    node.children[blk.tokens_hash] = child
                    self.node_count += 1
                # re-store under a new block_hash: drop the stale mapping
                # (invariant: table entries are {bh: node.workers[w]==bh})
                old = child.workers.get(worker)
                if old is not None and old != blk.block_hash:
                    table.pop(old, None)
                child.workers[worker] = blk.block_hash
                table[blk.block_hash] = child
                node = child
        elif isinstance(data, KvCacheRemoveData):
            for bh in data.block_hashes:
                node = table.pop(bh, None)
                if node is None:
                    continue
                if node.workers.get(worker) == bh:
                    del node.workers[worker]
                self._maybe_prune(node)

    def _maybe_prune(self, node: _Node) -> None:
        while (node.parent is not None and not node.workers
               and not node.children):
            parent = node.parent
            if parent.children.get(node.tokens_hash) is node:
                del parent.children[node.tokens_hash]
                self.node_count -= 1
            node = parent

    def remove_worker(self, worker: str) -> None:
        """Queue the worker's entries for incremental eviction and
        process one bounded chunk now. Small workers finish here (the
        pre-storm behavior); a 100k-node worker leaves a backlog that
        drains EVICT_AMORTIZE nodes per apply_event/find_matches (or via
        process_evictions) — meanwhile find_matches already answers as
        if the purge completed."""
        table = self.lookup.pop(worker, None)
        if not table:
            return
        items = deque(table.items())
        dq = self._evicting.get(worker)
        if dq is None:
            self._evicting[worker] = items
        else:
            dq.extend(items)
        self.process_evictions(EVICT_CHUNK)

    def process_evictions(self, budget: int = EVICT_CHUNK) -> int:
        """Drain up to `budget` pending eviction entries; returns the
        number processed. The block-hash guard makes a pending entry a
        no-op when the node's entry no longer belongs to the evicted
        generation (the worker re-stored through clear_all_blocks)."""
        done = 0
        while budget > 0 and self._evicting:
            worker, dq = next(iter(self._evicting.items()))
            while dq and budget > 0:
                bh, node = dq.popleft()
                if node.workers.get(worker) == bh:
                    del node.workers[worker]
                    self._maybe_prune(node)
                done += 1
                budget -= 1
            if not dq:
                del self._evicting[worker]
        return done

    def finish_eviction(self, worker: str) -> None:
        """Synchronously drain this worker's pending eviction (the
        revive path: a worker coming BACK must not stay hidden behind
        the find_matches eviction filter)."""
        dq = self._evicting.pop(worker, None)
        if not dq:
            return
        for bh, node in dq:
            if node.workers.get(worker) == bh:
                del node.workers[worker]
                self._maybe_prune(node)

    def eviction_backlog(self) -> int:
        return sum(len(dq) for dq in self._evicting.values())

    def clear_all_blocks(self, worker: str) -> None:
        """Worker restarted with an empty cache: drop its pages, keep it known."""
        self.remove_worker(worker)
        self.finish_eviction(worker)
        self.lookup[worker] = {}

    # -- introspection -------------------------------------------------------

    def num_nodes(self) -> int:
        # O(1): maintained at node create/prune (a periodic /metrics
        # refresh over a 100k-node tree cannot afford the full walk)
        return self.node_count

    def worker_block_count(self, worker: str) -> int:
        return len(self.lookup.get(worker, {}))


class KvIndexer:
    """Event-fed index: subscribe to `{ns}.{component}.kv_events` and answer
    overlap queries (reference indexer.rs:499-668)."""

    def __init__(self, block_size: int,
                 expiration_duration_s: Optional[float] = None,
                 native: object = "auto"):
        self.block_size = block_size
        # native C++ tree (dynamo_tpu/native/kv_indexer.cpp) when available;
        # the Python tree is the fallback and the frequency-tracking path
        self.tree = None
        if native and expiration_duration_s is None:
            try:  # lazy: native.radix imports MatchResult from this module
                from dynamo_tpu.native import radix
                if radix.available():
                    self.tree = radix.NativeRadixTree()
            except Exception:
                if native is True:
                    raise
        if self.tree is None:
            if native is True:
                raise RuntimeError("native kv indexer requested but "
                                   "unavailable")
            self.tree = RadixTree(expiration_duration_s)
        self.events_applied = 0
        # tombstones: in-flight events from a removed worker must not
        # resurrect it (they'd leak ghost nodes forever, since a worker
        # absent from the endpoint snapshot can never be removed again)
        self._removed: set = set()

    def apply_event(self, event: RouterEvent) -> None:
        if event.worker_id in self._removed:
            return
        self.tree.apply_event(event)
        self.events_applied += 1
        if self.events_applied % 256 == 0:
            self._refresh_cp_stats()

    def revive_worker(self, worker: str) -> None:
        """A worker id re-appeared live (restart): accept its events
        again — and drain any eviction still pending against its old
        generation, so the find_matches eviction filter cannot hide the
        revived worker's fresh pages."""
        self._removed.discard(worker)
        finish = getattr(self.tree, "finish_eviction", None)
        if finish is not None:
            finish(worker)

    def apply_raw(self, msg: dict) -> None:
        self.apply_event(RouterEvent.unpack(msg))

    def find_matches(self, page_hashes: Sequence[int]) -> MatchResult:
        return self.tree.find_matches(page_hashes)

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> MatchResult:
        return self.find_matches(
            compute_page_hashes(tokens, self.block_size))

    def remove_worker(self, worker: str) -> None:
        self._removed.add(worker)
        self.tree.remove_worker(worker)
        self._refresh_cp_stats()

    def process_evictions(self, budget: int = EVICT_CHUNK) -> int:
        """Drain pending incremental evictions (no-op on the native
        tree, whose remove_worker is synchronous C)."""
        proc = getattr(self.tree, "process_evictions", None)
        done = proc(budget) if proc is not None else 0
        if done:
            self._refresh_cp_stats()
        return done

    def eviction_backlog(self) -> int:
        backlog = getattr(self.tree, "eviction_backlog", None)
        return backlog() if backlog is not None else 0

    def num_nodes(self) -> int:
        return self.tree.num_nodes()

    def _refresh_cp_stats(self) -> None:
        CP_STATS.indexer_nodes = self.tree.num_nodes()
        CP_STATS.indexer_eviction_backlog = self.eviction_backlog()


class KvIndexerSharded:
    """Shards workers across independent trees (reference indexer.rs:677-900).

    Queries fan out to every shard and merge; events touch exactly one shard,
    so application parallelizes across owner tasks in a multi-loop deployment.
    """

    def __init__(self, block_size: int, num_shards: int = 4,
                 expiration_duration_s: Optional[float] = None):
        self.block_size = block_size
        self.shards = [KvIndexer(block_size, expiration_duration_s)
                       for _ in range(num_shards)]

    def _shard_for(self, worker: str) -> KvIndexer:
        # stable across processes/restarts — Python hash() is salted per
        # process (PYTHONHASHSEED), which would scatter a worker's events
        # across different shards after a restart (VERDICT r2 weak #6)
        h = xxhash.xxh3_64(worker.encode("utf-8"), seed=1337).intdigest()
        return self.shards[h % len(self.shards)]

    def apply_event(self, event: RouterEvent) -> None:
        self._shard_for(event.worker_id).apply_event(event)

    def find_matches(self, page_hashes: Sequence[int]) -> MatchResult:
        merged = MatchResult()
        for shard in self.shards:
            res = shard.find_matches(page_hashes)
            merged.scores.update(res.scores)
            # per-depth use counts sum across shards (each shard tracks its
            # own matched path; total recent uses of depth i is the sum)
            for i, f in enumerate(res.frequencies):
                if i < len(merged.frequencies):
                    merged.frequencies[i] += f
                else:
                    merged.frequencies.append(f)
        return merged

    def find_matches_for_tokens(self, tokens: Sequence[int]) -> MatchResult:
        return self.find_matches(compute_page_hashes(tokens, self.block_size))

    def remove_worker(self, worker: str) -> None:
        self._shard_for(worker).remove_worker(worker)

    def revive_worker(self, worker: str) -> None:
        self._shard_for(worker).revive_worker(worker)

    def process_evictions(self, budget: int = EVICT_CHUNK) -> int:
        done = 0
        for shard in self.shards:
            done += shard.process_evictions(budget)
        return done

    def eviction_backlog(self) -> int:
        return sum(s.eviction_backlog() for s in self.shards)

    def num_nodes(self) -> int:
        return sum(s.num_nodes() for s in self.shards)
