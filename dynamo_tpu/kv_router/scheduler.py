"""KV-aware worker selection.

Implements the reference's scheduler semantics (reference:
lib/llm/src/kv_router/scheduler.rs:88-340): given the query's per-worker
overlap scores (from the radix index) and each worker's load metrics, rank
workers by

    logit = overlap_weight * overlap_score - kv_usage - normalized_active

where `overlap_score = matched_blocks * block_size / isl` (fraction of the
prompt already resident), `kv_usage = kv_active_blocks / kv_total_blocks`,
and `normalized_active = request_active_slots / request_total_slots`
(reference DefaultWorkerSelector, scheduler.rs:236-340, cost at :290 with
overlap_weight=2). Ties break randomly; the chosen worker's active slots and
blocks are optimistically bumped so back-to-back schedules don't pile onto
one worker before the next metrics scrape lands
(process_worker_selection, scheduler.rs:208-232).
"""
from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Protocol

from dynamo_tpu.kv_router.indexer import MatchResult
from dynamo_tpu.kv_router.scoring import ProcessedEndpoints, WorkerMetrics


class AllWorkersBusy(Exception):
    pass


@dataclasses.dataclass
class SchedulingRequest:
    isl_tokens: int                # input sequence length in tokens
    overlap: MatchResult           # per-worker matched block counts


@dataclasses.dataclass
class WorkerSelection:
    worker_id: str
    required_blocks: int
    overlap_blocks: int


class WorkerSelector(Protocol):
    def select_worker(self, endpoints: ProcessedEndpoints,
                      request: SchedulingRequest,
                      block_size: int) -> WorkerSelection: ...


class DefaultWorkerSelector:
    def __init__(self, overlap_weight: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.overlap_weight = overlap_weight
        self.rng = rng or random.Random()

    def select_worker(self, endpoints: ProcessedEndpoints,
                      request: SchedulingRequest,
                      block_size: int) -> WorkerSelection:
        if not endpoints.workers:
            raise AllWorkersBusy("no live workers")
        isl = max(request.isl_tokens, 1)
        best_logit = float("-inf")
        best: List[str] = []
        for worker_id, m in endpoints.workers.items():
            matched = request.overlap.scores.get(worker_id, 0)
            overlap_score = matched * block_size / isl
            kv_usage = (m.kv_active_blocks / m.kv_total_blocks
                        if m.kv_total_blocks else 0.0)
            norm_active = (m.request_active_slots / m.request_total_slots
                           if m.request_total_slots else 0.0)
            logit = (self.overlap_weight * overlap_score
                     - kv_usage - norm_active)
            if logit > best_logit:
                best_logit, best = logit, [worker_id]
            elif logit == best_logit:
                best.append(worker_id)
        worker_id = self.rng.choice(best)
        required = -(-isl // block_size)
        return WorkerSelection(
            worker_id=worker_id, required_blocks=required,
            overlap_blocks=request.overlap.scores.get(worker_id, 0))


@dataclasses.dataclass
class KVHitRateEvent:
    """Published per scheduling decision on the event plane
    (reference scheduler.rs emits `kv-hit-rate` events)."""

    worker_id: str
    isl_blocks: int
    overlap_blocks: int


class KvScheduler:
    """Ranks workers for each request against the latest metrics snapshot.

    The endpoints snapshot is swapped in whole by the metrics aggregator's
    scrape loop (reference: watch channel of ProcessedEndpoints); optimistic
    bumps are applied to the current snapshot between scrapes.
    """

    def __init__(self, block_size: int,
                 selector: Optional[WorkerSelector] = None):
        self.block_size = block_size
        self.selector = selector or DefaultWorkerSelector()
        self.endpoints = ProcessedEndpoints()
        self.hit_events: List[KVHitRateEvent] = []

    def update_endpoints(self, endpoints: ProcessedEndpoints) -> None:
        self.endpoints = endpoints

    def remove_worker(self, worker_id: str) -> None:
        self.endpoints.workers.pop(worker_id, None)

    def schedule(self, isl_tokens: int, overlap: MatchResult,
                 exclude=()) -> str:
        """Pick a worker; `exclude` drops workers from consideration (the
        reliability layer's circuit breaker ejects flapping instances this
        way). If exclusion would empty the candidate set, the full set is
        used — a probe somewhere beats failing the request outright."""
        endpoints = self.endpoints
        if exclude:
            kept = {w: m for w, m in endpoints.workers.items()
                    if w not in exclude}
            if kept:
                # same WorkerMetrics objects: optimistic bumps below still
                # land on the live snapshot
                endpoints = ProcessedEndpoints(workers=kept)
        sel = self.selector.select_worker(
            endpoints, SchedulingRequest(isl_tokens, overlap),
            self.block_size)
        m = self.endpoints.workers.get(sel.worker_id)
        if m is not None:
            # optimistic accounting until the next scrape
            m.request_active_slots += 1
            m.kv_active_blocks += sel.required_blocks - sel.overlap_blocks
        self.hit_events.append(KVHitRateEvent(
            worker_id=sel.worker_id, isl_blocks=sel.required_blocks,
            overlap_blocks=sel.overlap_blocks))
        return sel.worker_id

    def drain_hit_events(self) -> List[KVHitRateEvent]:
        ev, self.hit_events = self.hit_events, []
        return ev
