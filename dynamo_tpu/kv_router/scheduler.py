"""KV-aware worker selection.

Implements the reference's scheduler semantics (reference:
lib/llm/src/kv_router/scheduler.rs:88-340): given the query's per-worker
overlap scores (from the radix index) and each worker's load metrics, rank
workers by

    logit = overlap_weight * overlap_score - kv_usage - normalized_active

where `overlap_score = matched_blocks * block_size / isl` (fraction of the
prompt already resident), `kv_usage = kv_active_blocks / kv_total_blocks`,
and `normalized_active = request_active_slots / request_total_slots`
(reference DefaultWorkerSelector, scheduler.rs:236-340, cost at :290 with
overlap_weight=2). Ties break randomly; the chosen worker's active slots and
blocks are optimistically bumped so back-to-back schedules don't pile onto
one worker before the next metrics scrape lands
(process_worker_selection, scheduler.rs:208-232).

**Transfer-aware scoring** (`TransferAwareSelector`, the serving
default; NetKV in PAPERS.md, ROADMAP item 3): disaggregated TTFT is
dominated by moving the non-overlapped KV pages to the chosen worker,
so the logit grows a fourth term —

    logit -= transfer_weight * min(max_penalty, cost_s / horizon_s)
    cost_s = estimate(link, bytes_to_move).seconds + queue_s(link)

with `bytes_to_move = (required - matched) blocks * page bytes` (the
worker's reported `kv_page_bytes`, falling back to
`default_block_bytes`), `estimate` the per-link measured-bandwidth
EWMA (observability/fleet.py TransferCostModel — delivered goodput,
resume overhead included) and `queue_s` the drain time of bytes
already in flight toward that destination. Cold links (no EWMA yet)
price at the fleet-median bandwidth with `cold=True` — never free,
never infinitely penalized. Under the router's stale-snapshot degraded
mode the cost term FREEZES at its last-good per-worker values
(`freeze_cost`) instead of recomputing from a snapshot known to be
stale — degradation must not amplify staleness into routing error.
Per-decision score components land in `last_components` /
`last_pick` for diagnosis and feed the llm_router_* gauges
(kv_router/stats.py).
"""
from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Protocol

from dynamo_tpu.kv_router.indexer import MatchResult
from dynamo_tpu.kv_router.scoring import ProcessedEndpoints, WorkerMetrics
from dynamo_tpu.kv_router.stats import ROUTER_STATS


class AllWorkersBusy(Exception):
    pass


@dataclasses.dataclass
class SchedulingRequest:
    isl_tokens: int                # input sequence length in tokens
    overlap: MatchResult           # per-worker matched block counts
    # leading query blocks fetchable from the cluster-wide shared KV
    # pool (engine/kv_pool.py; the router derives this from `pool:{w}`
    # index scores, live sources only). Fetchable blocks a candidate
    # does not hold locally reduce its bytes_to_move instead of counting
    # as misses — the fetch itself is priced with the same
    # TransferCostModel.estimate as a disagg transfer (docs/PERF.md §3e)
    pool_matched: int = 0
    # multi-tenant QoS (runtime/qos.py): the request's class name and
    # its latency weight — transfer-aware selectors SCALE the
    # transfer/backlog cost term by it, so latency-sensitive classes
    # avoid backlogged links first while batch tolerates them (1.0 =
    # class-neutral, the pre-QoS behavior)
    qos: str = ""
    qos_weight: float = 1.0


@dataclasses.dataclass
class WorkerSelection:
    worker_id: str
    required_blocks: int
    overlap_blocks: int


class WorkerSelector(Protocol):
    def select_worker(self, endpoints: ProcessedEndpoints,
                      request: SchedulingRequest,
                      block_size: int) -> WorkerSelection: ...


class DefaultWorkerSelector:
    def __init__(self, overlap_weight: float = 2.0,
                 rng: Optional[random.Random] = None):
        self.overlap_weight = overlap_weight
        self.rng = rng or random.Random()

    def select_worker(self, endpoints: ProcessedEndpoints,
                      request: SchedulingRequest,
                      block_size: int) -> WorkerSelection:
        if not endpoints.workers:
            raise AllWorkersBusy("no live workers")
        isl = max(request.isl_tokens, 1)
        best_logit = float("-inf")
        best: List[str] = []
        for worker_id, m in endpoints.workers.items():
            matched = request.overlap.scores.get(worker_id, 0)
            overlap_score = matched * block_size / isl
            kv_usage = (m.kv_active_blocks / m.kv_total_blocks
                        if m.kv_total_blocks else 0.0)
            norm_active = (m.request_active_slots / m.request_total_slots
                           if m.request_total_slots else 0.0)
            logit = (self.overlap_weight * overlap_score
                     - kv_usage - norm_active)
            if logit > best_logit:
                best_logit, best = logit, [worker_id]
            elif logit == best_logit:
                best.append(worker_id)
        worker_id = self.rng.choice(best)
        required = -(-isl // block_size)
        return WorkerSelection(
            worker_id=worker_id, required_blocks=required,
            overlap_blocks=request.overlap.scores.get(worker_id, 0))


class TransferAwareSelector(DefaultWorkerSelector):
    """DefaultWorkerSelector + a measured KV-transfer-cost penalty.

    The cost term is normalized against `horizon_s` (how many seconds
    of transfer outweigh one whole unit of load score) and capped at
    `max_penalty` so a single pathological link is strongly avoided
    without drowning every other signal. See the module docstring for
    the formula and the degraded-freeze semantics."""

    def __init__(self, overlap_weight: float = 2.0,
                 transfer_weight: float = 1.0,
                 horizon_s: float = 0.25,
                 max_penalty: float = 4.0,
                 default_block_bytes: int = 64 * 1024,
                 cost_model=None,
                 rng: Optional[random.Random] = None,
                 health_weight: float = 1.0,
                 health_of=None):
        super().__init__(overlap_weight, rng)
        self.transfer_weight = transfer_weight
        self.horizon_s = horizon_s
        self.max_penalty = max_penalty
        self.default_block_bytes = default_block_bytes
        if cost_model is None:
            from dynamo_tpu.observability.fleet import TRANSFER_MODEL
            cost_model = TRANSFER_MODEL
        self.cost_model = cost_model
        # fail-slow fold (runtime/health.py): health_of(worker) -> [0,1]
        # health score; the logit pays health_weight * (1 - health), so
        # a gray-failed worker sheds load BEFORE any breaker trips and a
        # fully healthy fleet (all scores 1.0) ranks exactly as before.
        # Defaults to the process-global HealthScorer.
        if health_of is None:
            from dynamo_tpu.runtime.health import HEALTH
            health_of = HEALTH.score
        self.health_of = health_of
        self.health_weight = health_weight
        # degraded-mode interaction: while frozen, per-worker cost
        # terms pin to their last live values (KvRouter flips this with
        # its stale-snapshot degraded flag)
        self.frozen = False
        self._frozen_cost: Dict[str, float] = {}
        # per-decision diagnosis: worker -> score components of the
        # LAST select_worker call, and the winner's row
        self.last_components: Dict[str, dict] = {}
        self.last_pick: Optional[dict] = None

    def freeze_cost(self, frozen: bool) -> None:
        """Enter/exit the degraded cost freeze. Entering keeps the
        last live per-worker costs; exiting clears them so the next
        decision recomputes from fresh signals."""
        if self.frozen and not frozen:
            self._frozen_cost.clear()
        self.frozen = frozen

    def _bytes_to_move(self, m: WorkerMetrics, required: int,
                       matched: int) -> int:
        block_bytes = m.kv_page_bytes or self.default_block_bytes
        return max(0, required - matched) * block_bytes

    def _cost_s(self, worker_id: str, nbytes: int) -> tuple:
        """(cost_s, cold) — live, or pinned under the degraded freeze.
        A frozen worker never seen live prices at the median of the
        pinned costs (not zero: unknown is not free)."""
        if self.frozen:
            known = self._frozen_cost
            if worker_id in known:
                return known[worker_id], False
            if known:
                vals = sorted(known.values())
                return vals[len(vals) // 2], True
            # frozen before any live decision: fall through to a live
            # estimate once — better than scoring everyone at zero
        est = self.cost_model.estimate(worker_id, nbytes)
        cost = est.seconds + self.cost_model.queue_s(worker_id)
        if not self.frozen:
            self._frozen_cost[worker_id] = cost
        return cost, est.cold

    def select_worker(self, endpoints: ProcessedEndpoints,
                      request: SchedulingRequest,
                      block_size: int) -> WorkerSelection:
        if not endpoints.workers:
            raise AllWorkersBusy("no live workers")
        isl = max(request.isl_tokens, 1)
        required = -(-isl // block_size)
        pool_m = max(0, min(request.pool_matched, required))
        best_logit = float("-inf")
        best: List[str] = []
        components: Dict[str, dict] = {}
        any_cold = False
        any_degraded = False
        if not self.frozen:
            # the pinned-cost table is "the last live decision's view":
            # rebuilt per decision (bounded by the candidate set) so a
            # freeze pins fresh values and dead workers can't linger
            self._frozen_cost.clear()
        for worker_id, m in endpoints.workers.items():
            matched = request.overlap.scores.get(worker_id, 0)
            # cluster-pool reuse (docs/PERF.md §3e): leading blocks the
            # pool holds BEYOND this worker's resident prefix are
            # fetchable, not misses — they join the overlap term and
            # shrink bytes_to_move, while the fetch bytes themselves are
            # priced below through the same cost model (cold estimates
            # answer from the fleet-median prior, never free)
            fetchable = max(0, pool_m - matched)
            eff_matched = matched + fetchable
            overlap_score = eff_matched * block_size / isl
            kv_usage = (m.kv_active_blocks / m.kv_total_blocks
                        if m.kv_total_blocks else 0.0)
            norm_active = (m.request_active_slots / m.request_total_slots
                           if m.request_total_slots else 0.0)
            nbytes_move = self._bytes_to_move(m, required, eff_matched)
            nbytes_fetch = fetchable * (m.kv_page_bytes
                                        or self.default_block_bytes)
            cost_s, cold = self._cost_s(worker_id,
                                        nbytes_move + nbytes_fetch)
            any_cold |= cold
            norm_cost = min(self.max_penalty, cost_s / self.horizon_s)
            # class-weighted cost (runtime/qos.py): an interactive
            # request (latency_weight > 1) pays the transfer/backlog
            # penalty harder and routes AROUND congested links first;
            # batch (< 1) tolerates them and soaks up the cheap slots.
            # qos_weight defaults to 1.0 — unclassed traffic scores
            # exactly as before.
            # fail-slow health fold: a degraded candidate pays
            # health_weight * (1 - score) — gray-failed workers shed
            # load before the latency breaker ever trips, and a score
            # of 1.0 (healthy or insufficient evidence) costs nothing
            health = self.health_of(worker_id)
            any_degraded |= health < 1.0
            logit = (self.overlap_weight * overlap_score
                     - kv_usage - norm_active
                     - self.transfer_weight * request.qos_weight
                     * norm_cost
                     - self.health_weight * (1.0 - health))
            components[worker_id] = {
                "qos": request.qos,
                "qos_weight": request.qos_weight,
                "overlap": round(overlap_score, 4),
                "kv_usage": round(kv_usage, 4),
                "active": round(norm_active, 4),
                "transfer_bytes": nbytes_move,
                "pool_blocks": fetchable,
                "pool_fetch_bytes": nbytes_fetch,
                "transfer_s": round(cost_s, 6),
                "transfer_norm": round(norm_cost, 4),
                "cold": cold,
                "frozen": self.frozen,
                "health": round(health, 4),
                "logit": round(logit, 4),
            }
            if logit > best_logit:
                best_logit, best = logit, [worker_id]
            elif logit == best_logit:
                best.append(worker_id)
        worker_id = self.rng.choice(best)
        self.last_components = components
        pick = dict(components[worker_id], worker_id=worker_id)
        self.last_pick = pick
        ROUTER_STATS.transfer_scored += 1
        if any_cold:
            ROUTER_STATS.cold_scored += 1
        if self.frozen:
            ROUTER_STATS.frozen_scored += 1
        if pool_m > 0:
            ROUTER_STATS.pool_scored += 1
        if any_degraded:
            ROUTER_STATS.health_scored += 1
        ROUTER_STATS.last_pick_health = pick["health"]
        ROUTER_STATS.last_pool_fetch_blocks = pick["pool_blocks"]
        ROUTER_STATS.last_transfer_est_s = pick["transfer_s"]
        ROUTER_STATS.last_transfer_bytes = pick["transfer_bytes"]
        ROUTER_STATS.est_err_abs_frac = round(
            self.cost_model.mean_abs_est_err(), 4)
        return WorkerSelection(
            worker_id=worker_id, required_blocks=required,
            overlap_blocks=request.overlap.scores.get(worker_id, 0))


@dataclasses.dataclass
class KVHitRateEvent:
    """Published per scheduling decision on the event plane
    (reference scheduler.rs emits `kv-hit-rate` events)."""

    worker_id: str
    isl_blocks: int
    overlap_blocks: int


class KvScheduler:
    """Ranks workers for each request against the latest metrics snapshot.

    The endpoints snapshot is swapped in whole by the metrics aggregator's
    scrape loop (reference: watch channel of ProcessedEndpoints); optimistic
    bumps are applied to the current snapshot between scrapes.
    """

    def __init__(self, block_size: int,
                 selector: Optional[WorkerSelector] = None):
        self.block_size = block_size
        self.selector = selector or DefaultWorkerSelector()
        self.endpoints = ProcessedEndpoints()
        self.hit_events: List[KVHitRateEvent] = []

    def update_endpoints(self, endpoints: ProcessedEndpoints) -> None:
        self.endpoints = endpoints

    def remove_worker(self, worker_id: str) -> None:
        self.endpoints.workers.pop(worker_id, None)

    def schedule(self, isl_tokens: int, overlap: MatchResult,
                 exclude=(), pool_matched: int = 0,
                 qos: str = "", qos_weight: float = 1.0) -> str:
        """Pick a worker; `exclude` drops workers from consideration (the
        reliability layer's circuit breaker ejects flapping instances this
        way). If exclusion would empty the candidate set, the full set is
        used — a probe somewhere beats failing the request outright.
        `pool_matched`: leading query blocks fetchable from the shared KV
        pool (live sources only — KvRouter derives it from the pool:
        index scores); pool-aware selectors fold it into scoring.
        `qos`/`qos_weight`: the request's QoS class + latency weight
        (runtime/qos.py) — class-aware selectors scale the transfer
        cost term by it."""
        endpoints = self.endpoints
        if exclude:
            kept = {w: m for w, m in endpoints.workers.items()
                    if w not in exclude}
            if kept:
                # same WorkerMetrics objects: optimistic bumps below still
                # land on the live snapshot
                endpoints = ProcessedEndpoints(workers=kept)
        sel = self.selector.select_worker(
            endpoints, SchedulingRequest(isl_tokens, overlap,
                                         pool_matched=pool_matched,
                                         qos=qos, qos_weight=qos_weight),
            self.block_size)
        m = self.endpoints.workers.get(sel.worker_id)
        if m is not None:
            # optimistic accounting until the next scrape
            m.request_active_slots += 1
            m.kv_active_blocks += sel.required_blocks - sel.overlap_blocks
        self.hit_events.append(KVHitRateEvent(
            worker_id=sel.worker_id, isl_blocks=sel.required_blocks,
            overlap_blocks=sel.overlap_blocks))
        return sel.worker_id

    def drain_hit_events(self) -> List[KVHitRateEvent]:
        ev, self.hit_events = self.hit_events, []
        return ev
