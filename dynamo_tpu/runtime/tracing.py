"""Deterministic, near-zero-overhead per-request tracing.

The reference threads `tracing` spans through every layer and hangs its
ops story off them (SURVEY.md: spans throughout, logging.rs); this is
that third plane rebuilt TPU-native. One process-global `TRACER` owns
per-THREAD append-only ring buffers — recording takes no locks (each
thread writes only its own ring; the registry lock is touched once, at
ring creation) and never syncs a device. Spans timestamp with
`time.monotonic()`; export (`drain()` + `chrome_trace()`) runs strictly
off the serving path.

Design rules, in overhead order:

- **Disabled (the default)**: every recording entry point is ONE branch
  (`if not self.enabled: return`). `span()` returns a pre-allocated
  module singleton, so a disabled `with TRACER.span(...)` allocates
  nothing. Hot-path behavior is bit-identical with tracing off.
- **Enabled, trace sampled out**: spans still run (so errors can be
  captured) but record only when `trace.sampled` or the span errored —
  seeded sampling drops the bytes, never the evidence of a failure.
- **Enabled + sampled**: a span is one small object and one tuple
  appended to the current thread's ring; rings are bounded (oldest
  records overwritten, `dropped` counted) so a storm cannot grow memory.
- **Hot-path regions** (`# dynalint: hot-path-begin/end`): even the
  span object is too much — `defer_phase()` appends the already-known
  (scope, name, duration) directly, which is how the engine's
  PhaseTimer plan/dispatch/fetch/commit splits become spans (dynalint
  R13 enforces that regions use this deferred form).

The trace CONTEXT (`trace_id`/`span_id`/sampled) rides
`runtime.engine.Context.baggage` under `TRACE_KEY`, so it crosses the
wire with every dispatch envelope for free (component.Client.generate
already ships baggage; the serving side rebuilds the Context and the
Context constructor re-hydrates `.trace`). Sampling is a pure function
of (seed, trace_id): every process that sees a trace id agrees on
whether it is sampled, with no coordination.

Span schema (one JSONL record per span after `drain()`):
    {"trace_id", "span_id", "parent_id", "name", "ts", "dur",
     "attrs", "error", "thread"}
`ts` is the process-local time.monotonic() start in seconds, `dur` in
seconds. `chrome_trace(spans)` converts a drained list into a
chrome://tracing-loadable dict. docs/OBSERVABILITY.md documents the
span names each layer emits and the "explain this slow request" flow.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
import uuid
import zlib
from typing import Any, Dict, List, Optional

# baggage / wire-frame key the serialized context travels under
TRACE_KEY = "trace"

_span_ids = itertools.count(1)   # CPython next() is atomic
# span ids must be unique across PROCESSES: a disagg trace merges span
# files from the frontend, decode and prefill processes, and a bare
# counter would collide (same "s1" everywhere) — corrupting parent
# links into cycles. One random prefix per process keeps id generation
# a counter bump + f-string.
_ID_PREFIX = uuid.uuid4().hex[:6]


def _new_span_id() -> str:
    return f"{_ID_PREFIX}-{next(_span_ids):x}"


class TraceContext:
    """The propagated triplet: which trace, which span children parent
    to, and the (root-decided) sampling verdict."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str = "", sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    def to_wire(self) -> Dict[str, Any]:
        return {"tid": self.trace_id, "sid": self.span_id,
                "s": 1 if self.sampled else 0}

    @classmethod
    def from_wire(cls, d: Optional[Dict[str, Any]]) -> Optional["TraceContext"]:
        if not d or "tid" not in d:
            return None
        return cls(str(d["tid"]), str(d.get("sid", "")), bool(d.get("s", 1)))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext({self.trace_id!r}, {self.span_id!r}, "
                f"sampled={self.sampled})")


class _Ring:
    """Bounded append-only record buffer; single-writer (its thread)."""

    __slots__ = ("recs", "cap", "pos", "dropped")

    def __init__(self, cap: int):
        self.recs: List[tuple] = []
        self.cap = cap
        self.pos = 0
        self.dropped = 0

    def append(self, rec: tuple) -> None:
        if len(self.recs) < self.cap:
            self.recs.append(rec)
        else:
            self.recs[self.pos] = rec
            self.pos = (self.pos + 1) % self.cap
            self.dropped += 1

    def snapshot(self) -> List[tuple]:
        return self.recs[self.pos:] + self.recs[:self.pos]

    def clear(self) -> None:
        self.recs = []
        self.pos = 0


class _NoopSpan:
    """The disabled-path singleton: every method is a no-op, `with`
    compatible, zero allocations per use."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:
        pass

    def context(self):
        return None


NOOP_SPAN = _NoopSpan()


class _Span:
    __slots__ = ("_tracer", "name", "trace", "parent_id", "span_id", "t0",
                 "attrs", "_done")

    def __init__(self, tracer: "Tracer", name: str, trace: TraceContext,
                 attrs: Optional[dict]):
        self._tracer = tracer
        self.name = name
        self.trace = trace
        self.parent_id = trace.span_id
        self.span_id = _new_span_id()
        self.t0 = time.monotonic()
        self.attrs = attrs
        self._done = False

    def set(self, **attrs) -> None:
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)

    def context(self) -> TraceContext:
        """A child context: same trace, this span as the parent."""
        return TraceContext(self.trace.trace_id, self.span_id,
                            self.trace.sampled)

    def finish(self, error: bool = False) -> None:
        if self._done:
            return
        self._done = True
        self._tracer._record(self.trace, self.span_id, self.parent_id,
                             self.name, self.t0, time.monotonic(),
                             self.attrs, error)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.finish(error=exc_type is not None)
        return False


class Tracer:
    """Process-global span recorder. See the module docstring for the
    overhead contract; knobs via env (DYN_TRACE / DYN_TRACE_SAMPLE /
    DYN_TRACE_SEED / DYN_TRACE_RING) or `configure()`."""

    def __init__(self):
        self.enabled = os.environ.get("DYN_TRACE", "") not in ("", "0")
        self.sample_rate = float(os.environ.get("DYN_TRACE_SAMPLE", "1.0"))
        self.seed = int(os.environ.get("DYN_TRACE_SEED", "0"))
        self.ring_capacity = int(os.environ.get("DYN_TRACE_RING", "65536"))
        self._local = threading.local()
        self._rings: List[tuple] = []        # (thread_name, _Ring)
        self._rings_lock = threading.Lock()

    # -- configuration --------------------------------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  sample_rate: Optional[float] = None,
                  seed: Optional[int] = None,
                  ring_capacity: Optional[int] = None) -> "Tracer":
        if enabled is not None:
            self.enabled = enabled
        if sample_rate is not None:
            self.sample_rate = sample_rate
        if seed is not None:
            self.seed = seed
        if ring_capacity is not None:
            self.ring_capacity = ring_capacity
        return self

    def reset(self) -> None:
        """Drop every recorded span (all threads' rings). Test helper —
        rings stay registered so live threads keep their fast path."""
        with self._rings_lock:
            for _name, ring in self._rings:
                ring.clear()

    # -- sampling -------------------------------------------------------------

    def sampled(self, trace_id: str) -> bool:
        """Pure function of (seed, trace_id): deterministic across
        processes and runs, no coordination needed."""
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        h = zlib.crc32(trace_id.encode(), self.seed) & 0xFFFFFFFF
        return h / 4294967296.0 < self.sample_rate

    def start_trace(self, trace_id: Optional[str] = None
                    ) -> Optional[TraceContext]:
        """Root a new trace (frontend ingest). None when disabled — the
        branch-only fast path."""
        if not self.enabled:
            return None
        tid = trace_id or uuid.uuid4().hex
        return TraceContext(tid, "", self.sampled(tid))

    # -- recording ------------------------------------------------------------

    def _ring(self) -> _Ring:
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = _Ring(self.ring_capacity)
            self._local.ring = ring
            with self._rings_lock:
                self._rings.append((threading.current_thread().name, ring))
        return ring

    def _record(self, trace: TraceContext, span_id: str, parent_id: str,
                name: str, t0: float, t1: float, attrs: Optional[dict],
                error: bool) -> None:
        if not (trace.sampled or error):
            return          # sampled out, but errors always survive
        self._ring().append((trace.trace_id, span_id, parent_id, name,
                             t0, t1, attrs, error))

    def span(self, name: str, trace: Optional[TraceContext],
             **attrs) -> "_Span | _NoopSpan":
        """Context-manager span. Disabled or trace-less: the shared
        no-op singleton (no allocation)."""
        if not self.enabled or trace is None:
            return NOOP_SPAN
        return _Span(self, name, trace, attrs or None)

    def scope_span(self, name: str, scope: str, **attrs) -> "_Span | _NoopSpan":
        """A span outside any request trace (engine windows, router
        storms): recorded under the pseudo-trace `scope:<scope>`."""
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, TraceContext(f"scope:{scope}"),
                     attrs or None)

    def begin_span(self, name: str, trace: Optional[TraceContext],
                   **attrs) -> Optional[_Span]:
        """Manual-lifecycle span: MUST be paired with `end_span` on every
        path (try/finally) — enforced by dynalint R13."""
        if not self.enabled or trace is None:
            return None
        return _Span(self, name, trace, attrs or None)

    def end_span(self, span: Optional[_Span], error: bool = False,
                 **attrs) -> None:
        if span is None:
            return
        if attrs:
            span.set(**attrs)
        span.finish(error=error)

    def event(self, name: str, trace: Optional[TraceContext],
              **attrs) -> None:
        """Zero-duration instant record (decode emits, injects)."""
        if not self.enabled or trace is None or not trace.sampled:
            return
        now = time.monotonic()
        self._ring().append((trace.trace_id, _new_span_id(),
                             trace.span_id, name, now, now,
                             attrs or None, False))

    def record_span(self, name: str, trace: Optional[TraceContext],
                    duration_s: float, **attrs) -> None:
        """Record an already-measured span ending now (e.g. a queue wait
        carried as a wall-clock delta across processes)."""
        if not self.enabled or trace is None or not trace.sampled:
            return
        now = time.monotonic()
        self._ring().append((trace.trace_id, _new_span_id(),
                             trace.span_id, name, now - max(0.0, duration_s),
                             now, attrs or None, False))

    def defer_phase(self, scope: str, name: str, dt_s: float) -> None:
        """The hot-path deferred recorder: no span object, no trace
        lookup — the caller already measured the phase (PhaseTimer), we
        append (scope, name, dt) and nothing else. The ONLY recording
        form allowed inside `# dynalint: hot-path-begin/end` regions
        (dynalint R13)."""
        if not self.enabled:
            return
        now = time.monotonic()
        self._ring().append((f"scope:{scope}", _new_span_id(), "",
                             name, now - dt_s, now, None, False))

    # -- export (off the serving path) ----------------------------------------

    def dropped(self) -> int:
        with self._rings_lock:
            return sum(ring.dropped for _n, ring in self._rings)

    def drain(self, clear: bool = True) -> List[Dict[str, Any]]:
        """Collect every recorded span from every thread's ring, oldest
        first. `clear=True` empties the rings (one capture per storm)."""
        with self._rings_lock:
            rings = list(self._rings)
        recs: List[tuple] = []
        for tname, ring in rings:
            for rec in ring.snapshot():
                recs.append(rec + (tname,))
            if clear:
                ring.clear()
        recs.sort(key=lambda r: r[4])
        return [{"trace_id": r[0], "span_id": r[1], "parent_id": r[2],
                 "name": r[3], "ts": r[4], "dur": r[5] - r[4],
                 "attrs": r[6], "error": r[7], "thread": r[8]}
                for r in recs]


def chrome_trace(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Convert drained spans into a chrome://tracing / Perfetto-loadable
    trace (JSON object format, "X" complete events + "i" instants).
    Threads map to tids; the trace_id rides in args."""
    if not spans:
        return {"traceEvents": []}
    t_base = min(s["ts"] for s in spans)
    tids: Dict[str, int] = {}
    events = []
    for s in spans:
        tid = tids.setdefault(s.get("thread", "main"), len(tids) + 1)
        args = dict(s.get("attrs") or {})
        args["trace_id"] = s["trace_id"]
        args["span_id"] = s["span_id"]
        if s.get("parent_id"):
            args["parent_id"] = s["parent_id"]
        if s.get("error"):
            args["error"] = True
        ev = {"name": s["name"], "pid": 1, "tid": tid,
              "ts": round((s["ts"] - t_base) * 1e6, 3), "args": args}
        if s["dur"] <= 0.0:
            ev.update(ph="i", s="t")
        else:
            ev.update(ph="X", dur=round(s["dur"] * 1e6, 3))
        events.append(ev)
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"source": "dynamo_tpu.runtime.tracing"}}


TRACER = Tracer()
