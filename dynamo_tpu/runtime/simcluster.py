"""Simulated O(1000)-worker cluster on the in-process control plane.

ROADMAP item 4: nothing validated that discovery, the radix prefix
indexer, watch fan-out, event-plane metrics, and scheduling hold up past
~4 workers. This module stands up a fleet of MOCK workers — no model, no
data plane: each is an instance key under its own lease, a live $STATS
responder, and a synthetic KV-event stream — plus one real `KvRouter` +
`Client` on the other side, then drives seeded CHAOS STORMS through the
control-plane failpoint sites (`runtime/faults.py`: watch.stream,
discovery.store, lease.expiry, event.plane) and through direct fleet
churn (rolling restarts, lease-expiry bursts) while a schedule-load
generator measures latency and enforces the routing contracts:

- **zero scheduling errors**: `KvRouter.schedule` never raises while
  capacity exists;
- **no corpse routing**: once a worker's delete/draining watch event has
  been APPLIED (the client listener fired), schedule() never returns it;
- **degraded-mode round trip**: an event-plane lag storm drives the
  router into — and back out of — the stale-snapshot degraded mode with
  no request errors.

Everything is seeded: storm target selection is a pure function of the
seed (`pick_storm_targets`), failpoint schedules are `FaultSchedule`s,
and re-registration jitter draws from per-worker seeded rngs — the same
plan replays the same storm. `tools/cluster_sim.py` is the CLI that runs
the capacity ladder and commits `SCALE_r07.json`.
"""
from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import random
import time
from typing import Dict, List, Optional

import msgpack

from dynamo_tpu.kv_router.protocols import (
    KvCacheEvent, KvCacheStoreData, KvCacheStoredBlockData, RouterEvent,
    compute_page_hashes,
)
from dynamo_tpu.kv_router.publisher import KV_EVENTS_SUBJECT
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.backoff import Backoff
from dynamo_tpu.runtime.component import STATUS_DRAINING
from dynamo_tpu.runtime.cpstats import CP_STATS
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.transports.memory import MemoryPlane

log = logging.getLogger("dynamo_tpu.simcluster")


@dataclasses.dataclass
class SimConfig:
    workers: int = 64
    streams: int = 1024          # logical streams cycling through the load gen
    prefix_families: int = 32    # distinct shared-prefix families (system prompts)
    family_pages: int = 8        # full KV pages per family prefix
    stores_per_worker: int = 4   # families each worker claims pages for
    block_size: int = 16
    lease_ttl_s: float = 3.0
    scrape_interval_s: float = 0.5
    degraded_lag_s: float = 0.75
    seed: int = 0
    namespace: str = "sim"
    component: str = "worker"
    endpoint: str = "generate"


def pick_storm_targets(seed: int, worker_ids: List[str],
                       fraction: float) -> List[str]:
    """Deterministic storm membership + order: a pure function of
    (seed, fleet, fraction) so a storm is replayable from its seed."""
    rng = random.Random(seed)
    ids = sorted(worker_ids)
    rng.shuffle(ids)
    count = max(1, int(len(ids) * fraction))
    return ids[:count]


def family_tokens(family: int, block_size: int, pages: int) -> List[int]:
    """Deterministic token prefix for one shared-prefix family."""
    return [(family * 977 + 31 * i) % 50000 for i in range(block_size * pages)]


def percentile(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return 0.0
    k = min(len(sorted_vals) - 1, int(p * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[k]


class SimWorker:
    """One mock worker: lease + instance key + $STATS responder +
    synthetic KV events. Deliberately NOT a DistributedRuntime — a
    thousand of those would each spawn lease-watch machinery the sim
    drives centrally instead."""

    def __init__(self, plane: MemoryPlane, cfg: SimConfig, worker_id: str,
                 rng: random.Random):
        self.plane = plane
        self.cfg = cfg
        self.worker_id = worker_id
        self.rng = rng
        self.lease = None
        self._unserve_stats = None
        self.alive = False          # heartbeat driver skips dead workers
        self.generation = 0
        self.backoff = Backoff(base_s=0.02, max_s=1.0, jitter=1.0,
                               stable_reset_s=5.0,
                               rng=random.Random(rng.randrange(1 << 30)))
        self._event_id = 0
        # serving role carried in the instance key ("prefill"/"decode";
        # None = aggregated wildcard) — what the autoscaler re-roles
        self.role: Optional[str] = None
        self.re_roles = 0

    # -- discovery ------------------------------------------------------------

    @property
    def key(self) -> str:
        c = self.cfg
        return (f"{c.namespace}/components/{c.component}/"
                f"{c.endpoint}:{self.worker_id}")

    @property
    def _subject(self) -> str:
        c = self.cfg
        return f"{c.namespace}|{c.component}.{c.endpoint}-{self.worker_id}"

    def _info(self, status: Optional[str] = None) -> bytes:
        c = self.cfg
        info = {"namespace": c.namespace, "component": c.component,
                "endpoint": c.endpoint, "worker_id": self.worker_id,
                "subject": self._subject}
        if self.role is not None:
            info["role"] = self.role
        if status:
            info["status"] = status
        return json.dumps(info).encode()

    async def _kv_retry(self, op, attempts: int = 8):
        """Discovery ops ride out store-unavailable windows (the
        discovery.store failpoint) with the worker's jittered backoff —
        what a real worker's registration loop does."""
        for i in range(attempts):
            try:
                return await op()
            except ConnectionError:
                if i == attempts - 1:
                    raise
                await self.backoff.sleep()

    async def register(self) -> None:
        self.lease = await self._kv_retry(
            lambda: self.plane.kv.grant_lease(self.cfg.lease_ttl_s))
        await self._kv_retry(
            lambda: self.plane.kv.put(self.key, self._info(),
                                      self.lease.id))

        async def stats(_payload: bytes) -> bytes:
            return msgpack.packb(self._stats())

        self._unserve_stats = await self.plane.messaging.serve(
            f"$STATS.{self._subject}", stats)
        self.alive = True
        self.generation += 1

    def _stats(self) -> dict:
        pages = self.cfg.family_pages * self.cfg.stores_per_worker
        return {
            "request_active_slots": self.rng.randrange(0, 8),
            "request_total_slots": 8,
            "kv_active_blocks": self.rng.randrange(0, pages + 1),
            "kv_total_blocks": max(pages, 1) * 4,
            "num_requests_waiting": 0,
            "gpu_cache_usage_perc": self.rng.random() * 0.5,
            "gpu_prefix_cache_hit_rate": self.rng.random(),
            # synthetic ledger figures (observability/ledger.py fields a
            # real engine publishes): the fleet rollup scrapes these, so
            # the 64-worker FLEET_r10 evidence exercises the same
            # WorkerMetrics plumbing a live fleet feeds it with
            "engine_steps": self._event_id * 7,
            "engine_tok_s": round(800.0 + self.rng.random() * 400.0, 1),
            "engine_pad_frac": round(self.rng.random() * 0.3, 3),
        }

    async def mark_draining(self) -> None:
        await self._kv_retry(
            lambda: self.plane.kv.put(self.key, self._info(STATUS_DRAINING),
                                      self.lease.id if self.lease else 0))

    async def assign_role(self, role: Optional[str]) -> None:
        """Declare/replace this worker's serving role in place (initial
        fleet split; NOT the re-role path — no drain fence)."""
        self.role = role
        if self.alive:
            await self._kv_retry(
                lambda: self.plane.kv.put(self.key, self._info(),
                                          self.lease.id if self.lease
                                          else 0))

    async def set_role(self, role: str) -> None:
        """Graceful re-role: the autoscaler's "this decode worker
        becomes a prefill worker" actuation, sim leg (the real-worker
        twin is `ServedEndpoint.re_role`). Fence ordering: DRAINING
        re-put under the OLD role first (watching routers drop it from
        `ids_for_role(old)` at event-apply time), then deregister +
        re-register under the new role — there is no window where the
        worker is schedulable for its old role."""
        if role == self.role:
            return
        await self.mark_draining()
        await asyncio.sleep(0)       # let the draining watch tick land
        await self.deregister()
        self.role = role
        await self.register()
        self.re_roles += 1

    async def deregister(self) -> None:
        self.alive = False
        await self._kv_retry(lambda: self.plane.kv.delete(self.key))
        if self.lease is not None:
            try:
                await self.lease.revoke()
            except ConnectionError:
                pass   # store window: lease expiry covers the revoke
            self.lease = None
        if self._unserve_stats is not None:
            await self._unserve_stats()
            self._unserve_stats = None

    def kill(self) -> None:
        """Process death: heartbeats stop, the lease expires on its own
        and the instance key vanishes through the lease-expiry path."""
        self.alive = False

    async def restart_with_jitter(self) -> float:
        """Re-registration with seeded jitter + flap hysteresis: the
        whole point is that a storm of restarts does NOT stampede
        discovery in one synchronized wave."""
        delay = self.backoff.next_delay()
        await asyncio.sleep(delay)
        await self.register()
        return delay

    # -- synthetic KV-event stream -------------------------------------------

    async def publish_family_pages(self, families: List[int],
                                   pages: Optional[int] = None) -> int:
        """Publish Stored chains claiming the first `pages` pages of each
        family prefix — the shape a real allocator emits after a prefill
        of a shared system prompt."""
        c = self.cfg
        n_events = 0
        for fam in families:
            toks = family_tokens(fam, c.block_size, c.family_pages)
            th = compute_page_hashes(toks, c.block_size)
            depth = pages if pages is not None else c.family_pages
            parent = None
            blocks = []
            for i in range(min(depth, len(th))):
                # block hashes are worker-unique chained ids; generation
                # salt keeps a restarted worker's chains distinct
                bh = hash((self.worker_id, self.generation, fam, i)) \
                    & 0x7FFFFFFFFFFFFFFF
                blocks.append(KvCacheStoredBlockData(bh, th[i]))
            ev = RouterEvent(
                self.worker_id,
                KvCacheEvent(self._event_id,
                             KvCacheStoreData(parent_hash=parent,
                                              blocks=blocks)),
                ts=time.time())
            self._event_id += 1
            await self.plane.messaging.publish(
                f"{c.namespace}.{c.component}.{KV_EVENTS_SUBJECT}",
                msgpack.packb(ev.pack()))
            n_events += 1
        return n_events


class SimCluster:
    """The harness: fleet + router + load generator + storm drivers."""

    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.rng = random.Random(cfg.seed)
        self.plane = MemoryPlane()
        self.workers: Dict[str, SimWorker] = {}
        self.rt = None
        self.client = None
        self.router: Optional[KvRouter] = None
        self._hb_task: Optional[asyncio.Task] = None
        # contract accounting
        self.schedule_errors = 0
        self.dead_picks = 0           # schedule returned a fenced worker
        self.schedule_calls = 0
        self.latencies_us: List[float] = []
        self._fenced: set = set()     # applied delete/draining fence
        # logical streams: (family, distinct suffix salt)
        self._streams = [(i % cfg.prefix_families, i)
                         for i in range(cfg.streams)]

    # -- lifecycle ------------------------------------------------------------

    async def start(self) -> "SimCluster":
        cfg = self.cfg
        self.rt = await DistributedRuntime.create_local(self.plane,
                                                        "sim-router")
        comp = self.rt.namespace(cfg.namespace).component(cfg.component)
        self.client = comp.endpoint(cfg.endpoint).client()
        await self.client.start()

        def on_instance(kind, worker_id, info):
            # the dead/draining fence the routing contract is checked
            # against: "after its watch event is applied" == after this
            # listener ran
            if kind == "delete":
                self._fenced.add(worker_id)
            elif info is not None and info.get("status") == STATUS_DRAINING:
                self._fenced.add(worker_id)
            else:
                self._fenced.discard(worker_id)

        self.client.add_listener(on_instance)
        self.router = await KvRouter(
            comp, self.client, cfg.block_size,
            scrape_interval_s=cfg.scrape_interval_s,
            degraded_lag_s=cfg.degraded_lag_s).start()

        t0 = time.perf_counter()
        ids = [f"w{i:04d}" for i in range(cfg.workers)]
        for i in range(0, len(ids), 64):      # registration waves
            wave = []
            for wid in ids[i:i + 64]:
                w = SimWorker(self.plane, cfg, wid,
                              random.Random(self.rng.randrange(1 << 30)))
                self.workers[wid] = w
                wave.append(w.register())
            await asyncio.gather(*wave)
        self.register_s = time.perf_counter() - t0

        t0 = time.perf_counter()
        for i in range(0, len(ids), 64):
            await asyncio.gather(*(
                self._seed_events(self.workers[wid]) for wid in ids[i:i + 64]))
        self.seed_events_s = time.perf_counter() - t0

        self._hb_task = asyncio.create_task(self._heartbeat_driver())
        await self.router.aggregator.scrape_once()
        await self._drain_event_queue()
        return self

    async def _seed_events(self, w: SimWorker) -> None:
        fams = [w.rng.randrange(self.cfg.prefix_families)
                for _ in range(self.cfg.stores_per_worker)]
        await w.publish_family_pages(fams)

    async def _heartbeat_driver(self) -> None:
        """One task heartbeats the whole fleet (a real fleet has one loop
        per process; the sim centralizes them to stay at one task)."""
        interval = self.cfg.lease_ttl_s / 3
        while True:  # dynalint: backoff-ok=fixed-cadence heartbeat driver, paced by lease TTL
            await asyncio.sleep(interval)
            for w in list(self.workers.values()):
                if w.alive and w.lease is not None:
                    keep = getattr(w.lease, "keep_alive", None)
                    if keep is not None:
                        try:
                            keep()
                        except faults.FaultInjected:
                            pass   # lost heartbeat: deadline not refreshed

    async def _drain_event_queue(self, timeout_s: float = 5.0) -> None:
        """Wait until the router has caught up with published events."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if CP_STATS.event_backlog == 0 and not self.router.degraded:
                return
            await asyncio.sleep(0.02)

    async def stop(self) -> None:
        if self._hb_task:
            self._hb_task.cancel()
        if self.router is not None:
            await self.router.stop()
        if self.client is not None:
            await self.client.stop()
        if self.rt is not None:
            await self.rt.shutdown()

    # -- load generation ------------------------------------------------------

    def _stream_tokens(self, stream_idx: int) -> List[int]:
        fam, salt = self._streams[stream_idx % len(self._streams)]
        cfg = self.cfg
        toks = family_tokens(fam, cfg.block_size, cfg.family_pages)
        # per-stream divergent suffix (under one page: doesn't index)
        return toks + [salt % 50000, (salt * 7) % 50000]

    async def schedule_once(self, stream_idx: int) -> Optional[str]:
        toks = self._stream_tokens(stream_idx)
        t0 = time.perf_counter()
        # storm trace capture (tools/cluster_sim.py --trace): one span
        # per schedule decision under the "router" scope; NOOP_SPAN
        # when tracing is off, so the capacity numbers are unaffected
        from dynamo_tpu.runtime.tracing import TRACER
        with TRACER.scope_span("router.schedule", "router",
                               stream=stream_idx) as sp:
            try:
                pick = await self.router.schedule(toks)
            except Exception:
                self.schedule_errors += 1
                log.exception("schedule failed for stream %d", stream_idx)
                sp.set(error_pick=True)
                return None
            finally:
                self.schedule_calls += 1
            sp.set(instance=pick)
        self.latencies_us.append((time.perf_counter() - t0) * 1e6)
        # contract: the fence reflects APPLIED watch events; a pick
        # inside it means the router routed onto a known corpse
        if pick in self._fenced:
            self.dead_picks += 1
            log.error("dead/draining worker %s picked post-fence", pick)
        return pick

    async def run_load(self, calls: int, concurrency: int = 32) -> dict:
        """Run `calls` schedule decisions at bounded concurrency; the
        per-call latency lands in self.latencies_us."""
        rng = random.Random(self.rng.randrange(1 << 30))
        sem = asyncio.Semaphore(concurrency)
        before = len(self.latencies_us)

        async def one(i: int):
            async with sem:
                await self.schedule_once(rng.randrange(len(self._streams)))

        t0 = time.perf_counter()
        await asyncio.gather(*(one(i) for i in range(calls)))
        wall = time.perf_counter() - t0
        lat = sorted(self.latencies_us[before:])
        return {"calls": calls, "wall_s": round(wall, 3),
                "calls_per_s": round(calls / wall, 1) if wall else 0.0,
                "p50_us": round(percentile(lat, 0.50), 1),
                "p99_us": round(percentile(lat, 0.99), 1)}

    # -- storms ---------------------------------------------------------------

    async def storm_rolling_restart(self, fraction: float = 0.3,
                                    batch: int = 8,
                                    load_calls: int = 0) -> dict:
        """Drain + deregister + jittered re-register a seeded fraction of
        the fleet, `batch` workers at a time, optionally under schedule
        load. Replacement workers re-register under the same id (a k8s
        rolling update), exercising fence-then-revive end to end."""
        targets = pick_storm_targets(self.rng.randrange(1 << 30),
                                     list(self.workers), fraction)
        load_task = (asyncio.create_task(self.run_load(load_calls))
                     if load_calls else None)
        t0 = time.perf_counter()
        jitters: List[float] = []
        for i in range(0, len(targets), batch):
            group = [self.workers[w] for w in targets[i:i + batch]]
            await asyncio.gather(*(w.mark_draining() for w in group))
            await asyncio.sleep(0)           # let the watch tick land
            await asyncio.gather(*(w.deregister() for w in group))

            async def revive(w: SimWorker):
                jitters.append(await w.restart_with_jitter())
                await self._seed_events(w)

            await asyncio.gather(*(revive(w) for w in group))
        storm_s = time.perf_counter() - t0
        if load_task is not None:
            load = await load_task
        else:
            load = None
        await self._drain_event_queue()
        return {"targets": len(targets), "storm_s": round(storm_s, 3),
                "mean_jitter_s": round(sum(jitters) / len(jitters), 4)
                if jitters else 0.0,
                "load": load,
                "errors": self.schedule_errors,
                "dead_picks": self.dead_picks}

    async def storm_lease_expiry(self, fraction: float = 0.2,
                                 load_calls: int = 0) -> dict:
        """Kill heartbeats for a seeded fraction; their leases expire in
        one burst (a mass watch-delete flood), then everyone restarts
        with jittered, hysteresis-grown delays."""
        targets = pick_storm_targets(self.rng.randrange(1 << 30),
                                     list(self.workers), fraction)
        load_task = (asyncio.create_task(self.run_load(load_calls))
                     if load_calls else None)
        for wid in targets:
            self.workers[wid].kill()
        # wait for the burst: every killed worker's key must vanish
        deadline = time.monotonic() + self.cfg.lease_ttl_s * 4
        while time.monotonic() < deadline:
            if all(w not in self.client.instances for w in targets):
                break
            await asyncio.sleep(0.05)
        expired = [w for w in targets if w not in self.client.instances]
        await asyncio.gather(*(self.workers[w].restart_with_jitter()
                               for w in targets))
        for wid in targets:
            await self._seed_events(self.workers[wid])
        if load_task is not None:
            await load_task
        await self._drain_event_queue()
        return {"targets": len(targets), "expired": len(expired),
                "errors": self.schedule_errors,
                "dead_picks": self.dead_picks}

    async def kill_fraction(self, fraction: float = 0.3,
                            wait_expiry: bool = True) -> List[str]:
        """Kill a seeded fraction of the fleet (heartbeats stop; leases
        expire) WITHOUT restarting — the two-phase primitive the fleet
        SLO storm (tools/fleet_storm.py) scrapes through: kill, watch
        the availability series burn, then `revive()` and watch the
        alert clear."""
        targets = pick_storm_targets(self.rng.randrange(1 << 30),
                                     list(self.workers), fraction)
        for wid in targets:
            self.workers[wid].kill()
        if wait_expiry:
            deadline = time.monotonic() + self.cfg.lease_ttl_s * 4
            while time.monotonic() < deadline:
                if all(w not in self.client.instances for w in targets):
                    break
                await asyncio.sleep(0.05)
        return targets

    async def revive(self, targets: List[str]) -> None:
        """Restart previously-killed workers (jittered) and re-seed
        their KV events — the recovery leg of the SLO storm."""
        await asyncio.gather(*(self.workers[w].restart_with_jitter()
                               for w in targets))
        for wid in targets:
            await self._seed_events(self.workers[wid])
        await self._drain_event_queue()

    async def storm_watch_disconnect(self, kills: int = 3,
                                     load_calls: int = 0) -> dict:
        """Arm the watch.stream failpoint to kill the next `kills` watch
        deliveries; every watcher must resume with backoff + resync. The
        convergence check registers fresh workers DURING the storm and
        asserts the client sees the exact live fleet afterwards."""
        resyncs_before = CP_STATS.watch_resyncs
        faults.REGISTRY.arm("watch.stream", faults.FaultSchedule(
            self.rng.randrange(1 << 30),
            [faults.FaultSpec("fail_n", n=kills)]))
        extra = []
        for i in range(2):
            wid = f"storm-extra-{len(self.workers) + i}"
            w = SimWorker(self.plane, self.cfg, wid,
                          random.Random(self.rng.randrange(1 << 30)))
            self.workers[wid] = w
            extra.append(w)
        await asyncio.gather(*(w.register() for w in extra))
        if load_calls:
            await self.run_load(load_calls)
        # convergence: the resumed watcher's resync must surface the
        # extras even though their put events died with the stream
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if all(w.worker_id in self.client.instances for w in extra):
                break
            await asyncio.sleep(0.05)
        faults.REGISTRY.disarm("watch.stream")
        converged = all(w.worker_id in self.client.instances for w in extra)
        return {"kills": kills,
                "resyncs": CP_STATS.watch_resyncs - resyncs_before,
                "converged": converged,
                "errors": self.schedule_errors,
                "dead_picks": self.dead_picks}

    async def storm_event_lag(self, delay_s: float = 1.5,
                              bursts: int = 4,
                              load_calls: int = 0) -> dict:
        """Arm event.plane delay so KV events arrive late (and out of
        order); the router must enter the stale-snapshot degraded mode,
        keep scheduling without errors, and exit once caught up."""
        entries_before = self.router.degraded_entries
        faults.REGISTRY.arm("event.plane", faults.FaultSchedule(
            self.rng.randrange(1 << 30),
            [faults.FaultSpec("delay", p=1.0, delay_s=delay_s)]))
        ids = list(self.workers)
        for _ in range(bursts):
            wids = [ids[self.rng.randrange(len(ids))] for _ in range(8)]
            await asyncio.gather(*(self._seed_events(self.workers[w])
                                   for w in wids))
            await asyncio.sleep(delay_s / bursts)
        if load_calls:
            await self.run_load(load_calls)
        # wait for the delayed deliveries to land and the lag to surface
        deadline = time.monotonic() + delay_s * 4 + 5.0
        entered = False
        while time.monotonic() < deadline:
            if self.router.degraded:
                entered = True
                break
            await asyncio.sleep(0.02)
        faults.REGISTRY.disarm("event.plane")
        # fresh (undelayed) events + idle ticks pull the lag back down
        await self._seed_events(self.workers[ids[0]])
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if not self.router.degraded:
                break
            await asyncio.sleep(0.05)
        return {"delay_s": delay_s,
                "entered": entered,
                "exited": not self.router.degraded,
                "degraded_entries":
                    self.router.degraded_entries - entries_before,
                "errors": self.schedule_errors,
                "dead_picks": self.dead_picks}

    # -- profiling ------------------------------------------------------------

    async def measure_scrape(self) -> float:
        t0 = time.perf_counter()
        await self.router.aggregator.scrape_once()
        return time.perf_counter() - t0

    async def event_rate_probe(self, events: int,
                               publishers: int = 32) -> dict:
        """Publish `events` Stored events as fast as the loop allows and
        measure how far the router's application lags behind arrival."""
        ids = list(self.workers)[:publishers]
        applied_before = self.router.events_applied
        t0 = time.perf_counter()
        per_pub = max(1, events // max(1, len(ids)))
        for start in range(0, per_pub):
            await asyncio.gather(*(
                self.workers[w].publish_family_pages(
                    [self.workers[w].rng.randrange(
                        self.cfg.prefix_families)], pages=1)
                for w in ids))
        publish_s = time.perf_counter() - t0
        peak_backlog = CP_STATS.event_backlog
        await self._drain_event_queue(timeout_s=30.0)
        total_s = time.perf_counter() - t0
        applied = self.router.events_applied - applied_before
        return {"published": per_pub * len(ids),
                "publish_s": round(publish_s, 3),
                "applied": applied,
                "applied_per_s": round(applied / total_s, 1)
                if total_s else 0.0,
                "peak_backlog": peak_backlog,
                "peak_lag_s": round(self.router.event_lag_s, 4),
                "drain_s": round(total_s - publish_s, 3)}

    # -- transfer-aware routing A/B (ISSUE 11 / ROADMAP item 3) ---------------

    async def routing_ab(self, requests: int = 2000,
                         block_bytes: int = 256 * 1024,
                         prefill_s: float = 0.04,
                         arrival_spacing_s: Optional[float] = None,
                         flaky_p: float = 0.25,
                         flaky_delay_s: float = 0.35,
                         cold_fraction: float = 0.15,
                         warm_samples: int = 3) -> dict:
        """Prefix-overlap-only vs transfer-aware scheduling over a fleet
        with HETEROGENEOUS link speeds, measured on simulated TTFT.

        Every per-link property is a pure function of the cluster seed:
        wire bandwidth draws from a two-decade tier ladder, and each
        link owns a seeded `transfer.link`-style delay FaultSchedule
        (the same FaultSpec machinery the chaos harness arms globally —
        here instantiated per link so flaky links stall deterministic
        transfers). A request's simulated TTFT = queue wait at its
        chosen worker + prefill + bytes_to_move/bandwidth + the seeded
        stall; bytes_to_move follows the radix index's REAL overlap for
        the chosen worker, so warm prefixes genuinely ship less. Both
        modes run the identical seeded request stream against
        identically seeded load snapshots; the transfer-aware mode's
        cost model learns only from the transfers the simulation
        completes (delivered goodput incl. stalls — lossy-link reality),
        with a seeded fraction of links left COLD to exercise the
        fleet-median fallback in anger.

        Returns a seeded-replayable report: per-mode TTFT percentiles
        and the p99/p50 improvement of transfer-aware over prefix-only
        (tools/routing_ab.py commits it as ROUTING_AB_r11.json)."""
        import heapq
        import zlib

        from dynamo_tpu.kv_router.scheduler import (
            DefaultWorkerSelector, TransferAwareSelector,
        )
        from dynamo_tpu.kv_router.scoring import (
            ProcessedEndpoints, WorkerMetrics,
        )
        from dynamo_tpu.observability.fleet import TransferCostModel

        seed = self.cfg.seed
        ids = sorted(self.workers)
        if arrival_spacing_s is None:
            # constant per-worker offered load regardless of fleet size
            # (~5 arrivals/s/worker): queueing pressure — the thing
            # transfer-aware backlog scoring manages — survives scaling
            # the A/B from the tier-1 smoke to the 1000-worker artifact
            arrival_spacing_s = 0.192 / max(1, len(ids))

        def link_seed(wid: str, salt: int) -> int:
            return (seed * 1000003 + salt) ^ zlib.crc32(wid.encode())

        # two-decade bandwidth ladder, seeded per link: most links are
        # datacenter-fast, a tail is congested/oversubscribed — the
        # heterogeneity transfer-aware routing exists to see
        tiers = (2e9, 8e8, 2e8, 1e7)
        weights = (0.4, 0.3, 0.2, 0.1)
        bw: Dict[str, float] = {}
        flaky: Dict[str, faults.FaultSchedule] = {}
        cold: set = set()
        for wid in ids:
            r = random.Random(link_seed(wid, 1))
            bw[wid] = r.choices(tiers, weights)[0]
            # per-link seeded delay faults (the transfer.link site's
            # delay kind, one schedule per link): slow links are also
            # likelier to stall
            p = flaky_p if bw[wid] <= 2e8 else flaky_p / 5
            flaky[wid] = faults.FaultSchedule(
                link_seed(wid, 2),
                [faults.FaultSpec("delay", p=p, delay_s=flaky_delay_s,
                                  delay_min_s=flaky_delay_s / 2)])
            if r.random() < cold_fraction:
                cold.add(wid)

        def seeded_endpoints() -> ProcessedEndpoints:
            # identical load snapshot for both modes (fresh objects:
            # optimistic bumps mutate them during a mode)
            pages = self.cfg.family_pages * self.cfg.stores_per_worker
            eps = ProcessedEndpoints()
            for wid in ids:
                r = random.Random(link_seed(wid, 3))
                eps.workers[wid] = WorkerMetrics(
                    request_active_slots=r.randrange(0, 8),
                    request_total_slots=8,
                    kv_active_blocks=r.randrange(0, pages + 1),
                    kv_total_blocks=max(pages, 1) * 4)
            return eps

        block_size = self.cfg.block_size

        def run_mode(selector, model) -> dict:
            self.router.scheduler.selector = selector
            self.router.scheduler.update_endpoints(seeded_endpoints())
            for sched in flaky.values():
                sched.reset()    # same seeded stall stream per mode
            if model is not None:
                # warm the measured-bandwidth table the way a live
                # fleet would (a few completed sends per link), minus
                # the seeded cold set — those exercise the fleet-median
                # fallback during the measured run
                for wid in ids:
                    if wid in cold:
                        continue
                    for k in range(warm_samples):
                        nb = block_bytes * (4 + k)
                        model.observe(wid, nb, nb / bw[wid])
            rng = random.Random(seed + 17)
            busy_until: Dict[str, float] = {}
            inflight: list = []    # (finish_t, wid, nbytes) heap
            ttfts: List[float] = []
            slow_picks = 0
            for i in range(requests):
                now = i * arrival_spacing_s
                while inflight and inflight[0][0] <= now:
                    _, fwid, fbytes = heapq.heappop(inflight)
                    if model is not None:
                        model.note_done(fwid, fbytes)
                toks = self._stream_tokens(
                    rng.randrange(len(self._streams)))
                overlap = self.router.find_matches_for_tokens(toks)
                pick = self.router.scheduler.schedule(len(toks), overlap)
                required = -(-len(toks) // block_size)
                matched = overlap.scores.get(pick, 0)
                nbytes = max(0, required - matched) * block_bytes
                stall = flaky[pick].decide().delay_s
                xfer_s = nbytes / bw[pick] + stall
                start = max(now, busy_until.get(pick, 0.0))
                finish = start + prefill_s + xfer_s
                busy_until[pick] = finish
                ttfts.append(finish - now)
                if bw[pick] <= 2e8:
                    slow_picks += 1
                if model is not None and nbytes > 0:
                    # the model learns delivered goodput incl. the
                    # seeded stall — lossy links estimate slower than
                    # their wire speed
                    model.observe(pick, nbytes, max(xfer_s, 1e-6))
                    model.note_inflight(pick, nbytes)
                    heapq.heappush(inflight, (finish, pick, nbytes))
            lat = sorted(ttfts)
            return {
                "requests": requests,
                "ttft_p50_ms": round(percentile(lat, 0.50) * 1e3, 2),
                "ttft_p95_ms": round(percentile(lat, 0.95) * 1e3, 2),
                "ttft_p99_ms": round(percentile(lat, 0.99) * 1e3, 2),
                "ttft_mean_ms": round(sum(lat) / len(lat) * 1e3, 2),
                "slow_link_picks": slow_picks,
            }

        saved = self.router.scheduler.selector
        try:
            prefix_only = run_mode(
                DefaultWorkerSelector(rng=random.Random(seed + 5)), None)
            model = TransferCostModel()
            aware_sel = TransferAwareSelector(
                cost_model=model, rng=random.Random(seed + 5),
                default_block_bytes=block_bytes)
            aware = run_mode(aware_sel, model)
        finally:
            self.router.scheduler.selector = saved
        return {
            "seed": seed,
            "workers": len(ids),
            "block_bytes": block_bytes,
            "bandwidth_tiers": list(tiers),
            "cold_links": len(cold),
            "flaky_delay_s": flaky_delay_s,
            "prefix_only": prefix_only,
            "transfer_aware": aware,
            "p99_improvement": round(
                1.0 - aware["ttft_p99_ms"]
                / max(prefix_only["ttft_p99_ms"], 1e-9), 4),
            "p50_improvement": round(
                1.0 - aware["ttft_p50_ms"]
                / max(prefix_only["ttft_p50_ms"], 1e-9), 4),
            "measured_links": len(model.links()),
            "mean_abs_est_err": round(model.mean_abs_est_err(), 4),
        }

    # -- fail-slow detection A/B (ISSUE 19) -----------------------------------

    async def fail_slow_ab(self, requests: int = 2000,
                           service_s: float = 0.05,
                           arrival_spacing_s: Optional[float] = None,
                           degraded_fraction: float = 0.08,
                           slow_factors: tuple = (4.0, 8.0, 16.0),
                           noise_frac: float = 0.05,
                           eval_interval_s: float = 0.25,
                           min_evidence: int = 6,
                           slow_share: float = 0.25,
                           hedge_quantile: float = 0.95,
                           hedge_min_delay_s: float = 0.02,
                           hedge_budget_frac: float = 0.1,
                           hedge_burst: int = 2,
                           replay_check: bool = True) -> dict:
        """Detection-OFF vs detection-ON (scoring + SLOW share + hedged
        dispatch) over a fleet with seeded GRAY-FAILED workers, measured
        on simulated TTFT — the fail-slow twin of `routing_ab`.

        A seeded fraction of workers is degraded through the persistent
        ``slow`` fault kind (runtime/faults.py): each owns a
        FaultSchedule with one ``FaultSpec("slow", p=1.0, factor=f)``,
        so its service time is multiplied by a seeded factor on every
        request it serves — alive, answering, dragging p99, exactly the
        failure the crash-stop planes cannot see. Both modes run the
        identical seeded arrival stream with per-request seeded service
        noise (noise draws key on the request index, not on mode
        decisions, so mode divergence cannot skew the comparison).

        OFF: least-backlog dispatch, blind to latency. ON: the same
        dispatch feeding a `HealthScorer` (virtual clock, evaluated at
        ``eval_interval_s``); a SLOW-marked worker keeps only
        ``slow_share`` of its dispatch (the residual traffic is the
        probe stream — never full eviction), and a request whose primary
        exceeds the adaptive TTFT quantile hedges once to the
        least-backlog healthy alternative under a per-class
        `HedgeBudget`, first token wins.

        Contracts checked here and gated by the chaos scenario:
        p99(ON) beats p99(OFF); ``dropped`` == 0 (every request produced
        a first token); ``false_ejections`` == 0 (no healthy worker ever
        marked SLOW — the min-evidence floor + MAD robustness at work);
        and with ``replay_check`` the ON mode runs twice and the SLOW
        decision timelines must be bit-identical (`timeline_replay_ok`).
        """
        import zlib

        from dynamo_tpu.runtime.health import HealthScorer, HedgeBudget

        seed = self.cfg.seed
        ids = sorted(self.workers)
        if arrival_spacing_s is None:
            # ~0.6 of fleet service capacity: loaded but not saturated,
            # so queue wait reflects dispatch quality, not overload
            arrival_spacing_s = service_s / (0.6 * max(1, len(ids)))

        def wseed(wid: str, salt: int) -> int:
            return (seed * 1000003 + salt) ^ zlib.crc32(wid.encode())

        # seeded gray-failure membership: persistent slow factor per
        # degraded worker via the "slow" fault kind
        degraded: Dict[str, faults.FaultSchedule] = {}
        factors: Dict[str, float] = {}
        for wid in ids:
            r = random.Random(wseed(wid, 11))
            if r.random() < degraded_fraction:
                f = slow_factors[r.randrange(len(slow_factors))]
                factors[wid] = f
                degraded[wid] = faults.FaultSchedule(
                    wseed(wid, 12),
                    [faults.FaultSpec("slow", p=1.0, factor=f)])
        if degraded_fraction > 0 and not degraded and ids:
            # tiny fleets must still contain one gray failure
            wid = ids[random.Random(seed + 13).randrange(len(ids))]
            factors[wid] = slow_factors[0]
            degraded[wid] = faults.FaultSchedule(
                wseed(wid, 12),
                [faults.FaultSpec("slow", p=1.0,
                                  factor=slow_factors[0])])

        def svc_time(wid: str, req_rng: random.Random) -> float:
            sf = (degraded[wid].decide().slow_factor
                  if wid in degraded else 1.0)
            return service_s * sf * (
                1.0 + noise_frac * (req_rng.random() * 2.0 - 1.0))

        def run_mode(detect: bool) -> dict:
            for sched in degraded.values():
                sched.reset()       # same seeded factor stream per mode
            scorer = HealthScorer(min_evidence=min_evidence,
                                  clock=lambda: 0.0)
            budget = HedgeBudget(hedge_budget_frac, hedge_burst)
            gate_rng = random.Random(seed + 29)   # SLOW-share dispatch
            busy = {w: 0.0 for w in ids}
            obs: List[float] = []                 # hedge-delay window
            ttfts: List[float] = []
            next_eval = eval_interval_s
            fired = wins = denied = dropped = 0
            for i in range(requests):
                now = i * arrival_spacing_s
                if detect:
                    while now >= next_eval:
                        scorer.evaluate(now=next_eval)
                        next_eval += eval_interval_s
                req_rng = random.Random(seed * 7919 + i)
                pick = min(ids, key=lambda w: (busy[w], w))
                if detect and scorer.is_slow(pick) \
                        and gate_rng.random() >= slow_share:
                    healthy = [w for w in ids if not scorer.is_slow(w)]
                    if healthy:
                        pick = min(healthy, key=lambda w: (busy[w], w))
                svc = svc_time(pick, req_rng)
                start = max(now, busy[pick])
                finish = start + svc
                busy[pick] = finish
                scorer.observe(pick, svc)
                ttft = finish - now
                if ttft != ttft or ttft < 0:       # pragma: no cover
                    dropped += 1                   # no first token
                budget.on_request("")
                if detect:
                    delay = (max(percentile(sorted(obs), hedge_quantile),
                                 hedge_min_delay_s)
                             if len(obs) >= 20 else float("inf"))
                    if ttft > delay:
                        if not budget.try_fire(""):
                            denied += 1
                        else:
                            alts = [w for w in ids if w != pick
                                    and not scorer.is_slow(w)]
                            if alts:
                                h = min(alts,
                                        key=lambda w: (busy[w], w))
                                hsvc = svc_time(h, req_rng)
                                hstart = max(now + delay, busy[h])
                                hfinish = hstart + hsvc
                                busy[h] = hfinish
                                scorer.observe(h, hsvc)
                                fired += 1
                                if hfinish < finish:
                                    # first token wins; the primary is
                                    # abandoned pre-commit
                                    wins += 1
                                    ttft = hfinish - now
                obs.append(ttft)
                del obs[:-200]
                ttfts.append(ttft)
            false_ej = sorted(w for w in scorer.slow_workers()
                              if w not in degraded)
            detected = sorted(w for w in scorer.slow_workers()
                              if w in degraded)
            lat = sorted(ttfts)
            return {
                "requests": requests,
                "ttft_p50_ms": round(percentile(lat, 0.50) * 1e3, 2),
                "ttft_p95_ms": round(percentile(lat, 0.95) * 1e3, 2),
                "ttft_p99_ms": round(percentile(lat, 0.99) * 1e3, 2),
                "ttft_mean_ms": round(sum(lat) / len(lat) * 1e3, 2),
                "dropped": dropped,
                "hedges_fired": fired,
                "hedge_wins": wins,
                "hedge_budget_denied": denied,
                "false_ejections": false_ej,
                "detected_slow": detected,
                "timeline": list(scorer.timeline),
            }

        off = run_mode(False)
        on = run_mode(True)
        replay_ok = True
        if replay_check:
            on2 = run_mode(True)
            replay_ok = (json.dumps(on["timeline"], sort_keys=True)
                         == json.dumps(on2["timeline"], sort_keys=True))
        return {
            "seed": seed,
            "workers": len(ids),
            "degraded_workers": len(degraded),
            "slow_factors": {w: factors[w] for w in sorted(factors)},
            "detection_off": off,
            "detection_on": on,
            "p99_improvement": round(
                1.0 - on["ttft_p99_ms"]
                / max(off["ttft_p99_ms"], 1e-9), 4),
            "p95_improvement": round(
                1.0 - on["ttft_p95_ms"]
                / max(off["ttft_p95_ms"], 1e-9), 4),
            "timeline_replay_ok": replay_ok,
        }

    # -- closed-loop autoscale storm (ISSUE 12 / ROADMAP item 4) --------------

    async def _await_fence(self, wid: str, timeout_s: float = 2.0) -> bool:
        """Wait until the client APPLIED the worker's draining/delete
        watch event (status draining or key gone) — the point after
        which the re-role fence contract is checkable."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            info = self.client.instances.get(wid)
            if info is None or info.get("status") == STATUS_DRAINING:
                return True
            await asyncio.sleep(0.005)
        return False

    async def _await_role_visible(self, wid: str, role: str,
                                  timeout_s: float = 2.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            info = self.client.instances.get(wid)
            if info is not None and info.get("role") == role \
                    and info.get("status") != STATUS_DRAINING:
                return True
            await asyncio.sleep(0.005)
        return False

    async def re_role_worker(self, wid: str, role: str,
                             old_role: Optional[str] = None) -> int:
        """Drive one worker's graceful re-role through the REAL control
        plane and enforce the drain-vs-schedule fence: after the
        draining event is applied, the worker must never appear in
        `ids_for_role(old_role)` again until its ready re-put under the
        NEW role. Returns the number of fence violations observed (0 =
        contract held). Storm-driver pacing (cooldown/hysteresis) is
        owned by the calling controller."""
        w = self.workers[wid]
        old_role = old_role if old_role is not None else w.role
        violations = 0
        await w.mark_draining()
        if await self._await_fence(wid) and old_role is not None \
                and wid in self.client.ids_for_role(old_role):
            violations += 1
            log.error("re-role fence violation: %s still schedulable "
                      "for %s after draining applied", wid, old_role)
        await w.set_role(role)
        await self._await_role_visible(wid, role)
        if old_role is not None and wid in self.client.ids_for_role(old_role):
            violations += 1
            log.error("re-role fence violation: %s schedulable for OLD "
                      "role %s after re-registering as %s",
                      wid, old_role, role)
        await self._seed_events(w)
        return violations

    async def autoscale_storm(self, traffic, ticks: int = 360,
                              n_prefill: Optional[int] = None,
                              controller: bool = True,
                              asc_cfg=None,
                              degraded_window: tuple = (0, 0),
                              prompt_tokens: tuple = (200, 600),
                              decode_tokens: tuple = (24, 96),
                              prefill_tok_s: float = 400.0,
                              decode_slots: int = 12,
                              base_itl_s: float = 0.05,
                              ttft_objective_s: float = 3.0,
                              itl_objective_s: float = 0.25,
                              drain_ticks: int = 2,
                              warmup_ticks: int = 10) -> dict:
        """Seeded diurnal + flash-crowd storm over a virtual clock: the
        closed-loop autoscaler evidence run (AUTOSCALE_r12.json).

        The TRAFFIC/SERVICE model is virtual and pure — arrivals,
        prefill-queue drain, decode-stream progress, TTFT/ITL samples
        are all functions of (seed, traffic shape, role layout) at
        integer virtual seconds — so the controller's decision timeline
        replays bit-identically from the same plan. The CONTROL PLANE
        is real: every re-role decision actuates through
        `SimWorker.set_role` (draining fence -> deregister ->
        re-register) against the live Client/watch machinery, with the
        drain-vs-schedule fence contract checked per actuation
        (`fence_violations` must stay 0).

        Per tick: arrivals join the prefill queue; active prefill
        workers drain it FIFO at `prefill_tok_s` (completions sample
        TTFT = completion - arrival and spawn a decode stream on the
        least-loaded non-draining decode worker); decode workers serve
        streams at `base_itl_s`, stretched by the over-subscription
        ratio when streams exceed `decode_slots`; the rollup-schema
        series (`serving/ttft_p95`, `serving/itl_p99`, `role/*/...`)
        are recorded at the virtual timestamp; the SLO watchdog
        evaluates; and — controller mode — the autoscaler ticks on
        `signals_from_store` over those same series. Re-role drains
        MIGRATE in-flight decode streams to surviving decode workers
        (`migrated` counted, `dropped` must stay 0); ticks inside
        `degraded_window` freeze the controller (zero decisions, the
        `frozen_degraded` counter advances instead).
        """
        from dynamo_tpu.observability.slo import SloSpec, SloWatchdog
        from dynamo_tpu.observability.timeseries import SeriesStore
        from dynamo_tpu.runtime.autoscaler import (
            ROLE_DECODE, ROLE_PREFILL, AutoscalerConfig, AutoscalerStats,
            FleetAutoscaler, signals_from_store,
        )
        cfg = self.cfg
        ids = sorted(self.workers)
        if n_prefill is None:
            n_prefill = len(ids) // 2
        # deterministic initial split, declared on the real instance keys
        role_of: Dict[str, str] = {}
        for i, wid in enumerate(ids):
            role_of[wid] = ROLE_PREFILL if i < n_prefill else ROLE_DECODE
        await asyncio.gather(*(self.workers[wid].assign_role(role_of[wid])
                               for wid in ids))

        store = SeriesStore(interval_s=1.0, capacity=max(600, ticks + 8))
        wd = SloWatchdog(store, [
            SloSpec(name="ttft_p95", series="serving/ttft_p95",
                    objective=ttft_objective_s, mode="above", target=0.9,
                    short_window_s=8.0, long_window_s=24.0,
                    burn_threshold=2.0, min_samples=3),
            SloSpec(name="itl_p99", series="serving/itl_p99",
                    objective=itl_objective_s, mode="above", target=0.9,
                    short_window_s=8.0, long_window_s=24.0,
                    burn_threshold=2.0, min_samples=3),
        ], degraded_fn=lambda: False)
        stats = AutoscalerStats()
        asc = FleetAutoscaler(
            asc_cfg or AutoscalerConfig(
                # role minimums at HALF the steady split: the do-no-harm
                # floor that keeps a lagging occupancy signal from
                # draining decode below its sustainable capacity
                min_prefill=max(1, n_prefill // 2),
                min_decode=max(1, (len(ids) - n_prefill) // 2),
                # actuation bounds scale with fleet size (2 moves per
                # decision is controller-speed at 16 workers and
                # wedged-slow at 64)
                cooldown_s=8.0, hysteresis_ticks=3,
                max_moves=max(2, len(ids) // 8),
                max_moves_per_window=max(10, len(ids) // 2),
                window_s=60.0,
                queue_hi=2.0, queue_lo=0.25, occ_hi=0.9, occ_lo=0.3,
                burn_hi=2.0,
                target_prefill_frac=n_prefill / max(1, len(ids))),
            stats=stats)

        # virtual fleet state
        draining: Dict[str, list] = {}       # wid -> [ticks_left, to_role]
        spares: List[str] = []               # shed workers (add pool)
        queue: List[list] = []               # [rid, arrival_ts, remaining]
        streams: Dict[str, List[list]] = {   # wid -> [[rid, remaining], ..]
            wid: [] for wid in ids if role_of[wid] == ROLE_DECODE}
        ttft_window: List[float] = []
        ttfts: List[float] = []
        completed = migrated = dropped = 0
        fence_violations = 0
        decisions_in_degraded = 0
        ttft_bad_ticks = itl_bad_ticks = 0
        peak_queue = 0.0
        rid_seq = 0
        req_rng_base = cfg.seed * 7919

        def active(role: str) -> List[str]:
            return [w for w, r in role_of.items()
                    if r == role and w not in draining]

        for t in range(ticks):
            ts = float(t)
            deg = degraded_window[0] <= t < degraded_window[1]
            # 1. arrivals
            for _ in range(traffic.arrivals(t)):
                rid_seq += 1
                r = random.Random(req_rng_base + rid_seq)
                queue.append([rid_seq, ts,
                              r.randint(*prompt_tokens),
                              r.randint(*decode_tokens)])
            peak_queue = max(peak_queue, float(len(queue)))
            # 2. prefill service (pooled FIFO drain)
            p_active = active(ROLE_PREFILL)
            capacity = len(p_active) * prefill_tok_s
            used = 0.0
            while queue and capacity > 0:
                item = queue[0]
                take = min(item[2], capacity)
                item[2] -= take
                capacity -= take
                used += take
                if item[2] <= 0:
                    queue.pop(0)
                    completed += 1
                    ttft = (ts + 1.0) - item[1]
                    ttfts.append(ttft)
                    ttft_window.append(ttft)
                    del ttft_window[:-50]
                    d_active = sorted(active(ROLE_DECODE),
                                      key=lambda w: (len(streams.get(w, ())),
                                                     w))
                    if d_active:
                        streams.setdefault(d_active[0], []).append(
                            [item[0], item[3]])
                    else:
                        dropped += 1     # no decode target: contract break
            p_occ = used / max(1.0, len(p_active) * prefill_tok_s)
            # 3. decode service
            itl_samples: List[float] = []
            d_active = active(ROLE_DECODE)
            total_streams = 0
            for wid in sorted(streams):
                ss = streams[wid]
                if not ss:
                    continue
                total_streams += len(ss)
                itl = base_itl_s * max(1.0, len(ss) / decode_slots)
                itl_samples.extend([itl] * len(ss))
                per_stream = 1.0 / itl
                for s in ss:
                    s[1] -= per_stream
                streams[wid] = [s for s in ss if s[1] > 0]
            total_slots = max(1, len(d_active) * decode_slots)
            d_occ = total_streams / total_slots
            # 4. drain progress: completions flip the role on the REAL
            # control plane and migrate leftover decode streams
            for wid in list(draining):
                draining[wid][0] -= 1
                if draining[wid][0] > 0:
                    continue
                to_role = draining.pop(wid)[1]
                leftover = streams.pop(wid, [])
                if leftover:
                    targets = sorted(active(ROLE_DECODE),
                                     key=lambda w: (len(streams.get(w, ())),
                                                    w))
                    if targets:
                        for i, s in enumerate(leftover):
                            streams.setdefault(
                                targets[i % len(targets)], []).append(s)
                        migrated += len(leftover)
                    else:
                        dropped += len(leftover)
                old = role_of.pop(wid)
                if to_role is None:       # shed: park the worker
                    spares.append(wid)
                    await self.workers[wid].mark_draining()
                    await self.workers[wid].deregister()
                else:
                    role_of[wid] = to_role
                    if to_role == ROLE_DECODE:
                        streams.setdefault(wid, [])
                    fence_violations += await self.re_role_worker(
                        wid, to_role, old_role=old)
            # 5. record the rollup-schema series at the virtual ts
            rec = store.record
            if ttft_window:
                rec("serving/ttft_p95",
                    percentile(sorted(ttft_window), 0.95), ts)
            rec("serving/itl_p99",
                percentile(sorted(itl_samples), 0.99)
                if itl_samples else base_itl_s, ts)
            for role, occ, qd in ((ROLE_PREFILL, p_occ, float(len(queue))),
                                  (ROLE_DECODE, d_occ,
                                   float(max(0, total_streams
                                             - total_slots)))):
                ready = len(active(role))
                drn = sum(1 for w in draining if role_of.get(w) == role)
                rec(f"role/{role}/workers", float(ready), ts)
                rec(f"role/{role}/draining", float(drn), ts)
                rec(f"role/{role}/queue_depth", qd, ts)
                rec(f"role/{role}/occupancy", occ, ts)
                rec(f"role/{role}/availability",
                    ready / max(1, ready + drn), ts)
            sv = store.get("serving/ttft_p95")
            if sv is not None and sv.latest() is not None \
                    and sv.latest() > ttft_objective_s:
                ttft_bad_ticks += 1
            if (store.get("serving/itl_p99").latest() or 0.0) \
                    > itl_objective_s:
                itl_bad_ticks += 1
            # 6. watchdog + controller (warmup ticks give the series
            # their first samples before the controller may act)
            wd.evaluate(ts)
            if not controller or t < warmup_ticks:
                continue
            sig = signals_from_store(store, wd, ts, degraded=deg,
                                     drains_active=len(draining))
            candidates = {
                ROLE_DECODE: sorted(active(ROLE_DECODE),
                                    key=lambda w: (len(streams.get(w, ())),
                                                   w)),
                ROLE_PREFILL: sorted(active(ROLE_PREFILL)),
            }
            decisions = asc.decide(sig, candidates)
            if deg and decisions:
                decisions_in_degraded += len(decisions)
            for d in decisions:
                if d.kind in ("re_role_to_prefill", "re_role_to_decode"):
                    for wid in d.workers:
                        draining[wid] = [drain_ticks, d.to_role]
                        await self.workers[wid].mark_draining()
                        if await self._await_fence(wid) and \
                                wid in self.client.ids_for_role(
                                    role_of[wid]):
                            fence_violations += 1
                elif d.kind == "shed":
                    for wid in d.workers:
                        draining[wid] = [drain_ticks, None]
                        await self.workers[wid].mark_draining()
                elif d.kind == "add":
                    for _ in range(d.count):
                        if not spares:
                            break
                        wid = spares.pop()
                        role_of[wid] = d.to_role
                        if d.to_role == ROLE_DECODE:
                            streams.setdefault(wid, [])
                        w = self.workers[wid]
                        w.role = d.to_role
                        await w.register()
                        await self._seed_events(w)

        lat = sorted(ttfts)
        report = {
            "mode": "controller" if controller else "static",
            "workers": len(ids),
            "n_prefill_initial": n_prefill,
            "ticks": ticks,
            "requests": rid_seq,
            "completed": completed,
            "ttft_p50_s": round(percentile(lat, 0.50), 3),
            "ttft_p95_s": round(percentile(lat, 0.95), 3),
            "ttft_p99_s": round(percentile(lat, 0.99), 3),
            "peak_queue": peak_queue,
            "slo": {
                "ttft_bad_ticks": ttft_bad_ticks,
                "itl_bad_ticks": itl_bad_ticks,
                "alerts": list(wd.alerts),
                "firing_at_end": wd.firing(),
            },
            "streams": {"completed": completed, "migrated": migrated,
                        "dropped": dropped},
            "roles_final": {
                "prefill": len(active(ROLE_PREFILL)),
                "decode": len(active(ROLE_DECODE)),
                "spares": len(spares),
            },
            "fence_violations": fence_violations,
            "degraded_window": list(degraded_window),
        }
        if controller:
            report["controller"] = asc.summary()
            report["controller"]["frozen_degraded"] = stats.frozen_degraded
            report["controller"]["cooldown_suppressed"] = \
                stats.cooldown_suppressed
            report["controller"]["hysteresis_suppressed"] = \
                stats.hysteresis_suppressed
            report["controller"]["guard_blocked"] = stats.guard_blocked
            report["decisions_in_degraded"] = decisions_in_degraded
        return report

    def summary(self) -> dict:
        lat = sorted(self.latencies_us)
        return {
            "workers": len(self.workers),
            "streams": self.cfg.streams,
            "schedule_calls": self.schedule_calls,
            "schedule_errors": self.schedule_errors,
            "dead_picks": self.dead_picks,
            "p50_us": round(percentile(lat, 0.50), 1),
            "p99_us": round(percentile(lat, 0.99), 1),
            "register_s": round(self.register_s, 3),
            "indexer_nodes": self.router.indexer.num_nodes(),
            "eviction_backlog": self.router.indexer.eviction_backlog(),
            "watch_resyncs": CP_STATS.watch_resyncs,
            "degraded_entries": self.router.degraded_entries,
        }
