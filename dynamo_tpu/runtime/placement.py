"""Consistent-hash placement for the cross-host KV pool service.

The in-process `SharedKvPool` (engine/kv_pool.py) made sealed KV pages a
cluster namespace; this module decides WHERE in the cluster each page
lives once the pool is served by multiple hosts (engine/pool_service.py).
The placement primitive is the classic consistent-hash ring with virtual
nodes (the memcached/Dynamo shape the LMCache tier survey assumes):

- **`HashRing`** — each pool host owns `vnodes` points on a 64-bit ring;
  a page hash's owners are the first R DISTINCT hosts clockwise from the
  key's point. Virtual nodes bound load skew (stddev/mean falls as
  1/sqrt(vnodes*hosts)); walking clockwise makes replica sets of
  adjacent keys overlap, which is what keeps rebalance traffic minimal:
  a join steals only the arcs it lands on, a leave promotes exactly the
  next host on each arc.

- **Ownership epoch** — bumped on EVERY membership change (join, leave,
  explicit bump). The epoch is the pool's write fence, playing the role
  `alloc_epoch` plays for transfer senders (disagg/remote_transfer.py
  StaleEpochError): a publisher or rebalancer that computed owners under
  an old ring must not land bytes on a host that no longer owns the key
  — the serving host rejects the stale-epoch write by name, and the
  writer recomputes owners under the current membership. Without the
  fence, a rebalance racing a membership change can resurrect an entry
  onto a host the new ring never chose, where no fetcher will look and
  no future rebalance will repair.

- **`PoolMembership`** — the liveness view threaded through the router
  (KvRouter._split_pool_scores) and the fetch-side replica walk. It IS
  the ring plus a watch-event feed (`on_instance`, the
  `Client.add_listener` callback shape): a pool host's instance delete
  removes it from membership at event time, so a dead host's
  fetchable-prefix scores stop pricing routes immediately — the PR-4
  corpse-routing fence, extended to pool HOSTS (the PR-13 eviction only
  fenced pool *sources*, i.e. publishing workers).

Determinism: hashing is blake2b over stable strings — the same
membership always yields the same ring, so placement is reproducible
across processes and replayable chaos runs (tools/chaos_replay.py).
"""
from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "HashRing", "PoolMembership", "POOL_HOST_INSTANCE_PREFIX",
    "pool_host_instance_id", "is_pool_host_instance",
    "pool_host_of_instance",
]

# Pool hosts advertise themselves as component instances under this
# worker-id prefix (next to the engine workers the router already
# watches), so ONE instance watch feeds both the corpse-routing fence
# and pool-host membership — mirror of kv_router/protocols.py's
# `pool:{worker_id}` source-id convention.
POOL_HOST_INSTANCE_PREFIX = "pool-host:"


def pool_host_instance_id(host: str) -> str:
    return f"{POOL_HOST_INSTANCE_PREFIX}{host}"


def is_pool_host_instance(worker_id: str) -> bool:
    return worker_id.startswith(POOL_HOST_INSTANCE_PREFIX)


def pool_host_of_instance(worker_id: str) -> str:
    return worker_id[len(POOL_HOST_INSTANCE_PREFIX):]


def _point(s: str) -> int:
    """Stable 64-bit ring point (blake2b — fast, seedless, identical
    across processes; hash() is salted per-process and unusable here)."""
    return int.from_bytes(
        hashlib.blake2b(s.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Consistent-hash ring with virtual nodes + ownership epoch.

    Thread-safe: membership changes arrive from watch pumps while
    engine threads resolve owners during prefix walks. `replicas` is R,
    the target copy count per key (default 2 — one host death never
    loses an entry); `owners_for` returns min(R, hosts) distinct hosts,
    so a one-host ring degrades to R=1 rather than failing.
    """

    def __init__(self, vnodes: int = 64, replicas: int = 2):
        if vnodes < 1 or replicas < 1:
            raise ValueError("vnodes and replicas must be >= 1")
        self.vnodes = vnodes
        self.replicas = replicas
        self.epoch = 0                       # ownership epoch (write fence)
        self._hosts: Dict[str, None] = {}    # insertion-ordered set
        self._points: List[int] = []         # sorted vnode points
        self._owner_at: List[str] = []       # host owning _points[i]
        self._mu = threading.RLock()

    # -- membership -----------------------------------------------------------

    def __len__(self) -> int:
        with self._mu:
            return len(self._hosts)

    def __contains__(self, host: str) -> bool:
        with self._mu:
            return host in self._hosts

    @property
    def hosts(self) -> Tuple[str, ...]:
        with self._mu:
            return tuple(self._hosts)

    def add(self, host: str) -> bool:
        """Join. Returns True when membership changed (and the ownership
        epoch was bumped — every placement computed before this call is
        now stale and must be fenced by the serving hosts)."""
        with self._mu:
            if host in self._hosts:
                return False
            self._hosts[host] = None
            for v in range(self.vnodes):
                p = _point(f"{host}#{v}")
                i = bisect.bisect_left(self._points, p)
                self._points.insert(i, p)
                self._owner_at.insert(i, host)
            self.epoch += 1
            return True

    def remove(self, host: str) -> bool:
        """Leave (death or drain). Returns True when membership changed
        (ownership epoch bumped — see `add`)."""
        with self._mu:
            if host not in self._hosts:
                return False
            del self._hosts[host]
            keep = [(p, h) for p, h in zip(self._points, self._owner_at)
                    if h != host]
            self._points = [p for p, _ in keep]
            self._owner_at = [h for _, h in keep]
            self.epoch += 1
            return True

    # -- placement ------------------------------------------------------------

    def owners_for(self, key: int, r: Optional[int] = None) -> List[str]:
        """The first min(r, hosts) DISTINCT hosts clockwise from `key`'s
        ring point, in ring order — element 0 is the primary, the rest
        are replicas. Deterministic for a given membership; every
        consumer must treat the result as valid only under the current
        ownership epoch (membership changes invalidate it — the serving
        host's stale-epoch fence catches writers that don't recheck)."""
        r = self.replicas if r is None else r
        with self._mu:
            if not self._points:
                return []
            r = min(r, len(self._hosts))
            i = bisect.bisect_right(self._points, _point(f"k{key:x}"))
            owners: List[str] = []
            n = len(self._points)
            for step in range(n):
                h = self._owner_at[(i + step) % n]
                if h not in owners:
                    owners.append(h)
                    if len(owners) == r:
                        break
            return owners

    def owners_with_epoch(self, key: int,
                          r: Optional[int] = None) -> Tuple[int, List[str]]:
        """Atomic (epoch, owners) snapshot under ONE lock hold — the
        pair a fenced write needs: owners computed under a ring tagged
        with THAT ring's epoch. Reading `epoch` and `owners_for` as two
        separate calls lets a membership change slip between them,
        yielding new-ring owners tagged with the old epoch — every
        serving host then fences the write and a healthy publish
        reports unavailable."""
        with self._mu:
            return self.epoch, self.owners_for(key, r)

    def lookup(self, key: int) -> Optional[str]:
        """Primary owner only (epoch-fenced like owners_for: valid for
        the current membership epoch, rechecked by the serving host)."""
        owners = self.owners_for(key, r=1)
        return owners[0] if owners else None

    def snapshot(self) -> dict:
        with self._mu:
            return {"hosts": list(self._hosts), "epoch": self.epoch,
                    "vnodes": self.vnodes, "replicas": self.replicas}


class PoolMembership:
    """Watch-fed pool-host liveness view (ring membership + event feed).

    One object shared by: the cluster pool (placement + rebalance
    trigger), and the router (`KvRouter._split_pool_scores` — a pool
    prefix is only fetchable while SOME member can serve it, so an
    empty membership zeroes pool pricing at watch-event time).

    `on_instance(kind, worker_id, info)` is `Client.add_listener`
    callback-shaped: pool-host instance puts join the ring, deletes
    leave it (each bumping the ownership epoch); non-pool-host instance
    events are ignored, so the same listener can watch a mixed
    component. Callbacks registered via `on_change(cb)` run
    synchronously after each membership change — the cluster pool hangs
    its rebalance trigger there (kept cheap: the listener only ENQUEUES
    rebalance work; the copies run under `run_rebalance`'s bounded
    budget, the PR-4 drain discipline)."""

    def __init__(self, ring: Optional[HashRing] = None):
        self.ring = ring if ring is not None else HashRing()
        self._change_cbs: List = []

    def on_change(self, cb) -> None:
        """cb(kind, host, epoch) after each membership change
        (kind 'join'/'leave'); runs synchronously — keep it cheap."""
        self._change_cbs.append(cb)

    def live_hosts(self) -> Tuple[str, ...]:
        return self.ring.hosts

    @property
    def epoch(self) -> int:
        return self.ring.epoch

    def join(self, host: str) -> bool:
        changed = self.ring.add(host)
        if changed:
            self._fire("join", host)
        return changed

    def leave(self, host: str) -> bool:
        changed = self.ring.remove(host)
        if changed:
            self._fire("leave", host)
        return changed

    def _fire(self, kind: str, host: str) -> None:
        for cb in list(self._change_cbs):
            cb(kind, host, self.ring.epoch)

    def on_instance(self, kind: str, worker_id: str, info) -> None:
        """Client.add_listener-compatible watch feed."""
        if not is_pool_host_instance(worker_id):
            return
        host = pool_host_of_instance(worker_id)
        if kind == "delete":
            self.leave(host)
        elif kind == "put":
            self.join(host)

    def owners_for(self, key: int, r: Optional[int] = None) -> List[str]:
        # placement answers are epoch-scoped: pair with `epoch` and let
        # the serving host's stale-epoch fence reject a racing change
        return self.ring.owners_for(key, r)

    def owners_with_epoch(self, key: int,
                          r: Optional[int] = None) -> Tuple[int, List[str]]:
        # the atomic pairing of the two reads above — what an
        # epoch-fenced write path must use (HashRing.owners_with_epoch)
        return self.ring.owners_with_epoch(key, r)
