"""KV data-plane integrity: per-page checksums, verify-on-fetch, quarantine.

The reference's data plane (NIXL/RDMA KV transfer, multi-tier offload)
silently trusts every byte; FlowKV and LMCache (PAPERS.md) both report
that once KV pages cross transports and storage tiers, corruption and
stale/partial pages become the dominant correctness hazard — not
crashes. The contract this module anchors: **a corrupted transfer or
tier read may cost latency, but can never change emitted tokens.**

Mechanics (the state machine is drawn out in docs/RESILIENCE.md):

- a checksum is computed **at capture** — the moment page bytes leave
  the authoritative copy (staged on the prefill host for a transfer,
  handed to the host pool for an offload) — and travels WITH the page
  across every hop and tier; it is never recomputed from a copy that
  could already be corrupt (recomputing would launder corruption).
- every consumer **verifies on fetch** (transfer inject, tier read)
  before the bytes can reach the device cache.
- a transfer mismatch triggers a **bounded re-fetch** (the sender still
  holds the authoritative pages); a tier mismatch **quarantines** the
  entry (dropped from the tier, counted) so the prefix walk misses and
  the page is recomputed.
- persistent transfer mismatch gives up on the remote path entirely and
  falls back to **re-prefill** (the PR 2 `resume_committed`/local
  recompute machinery) — degraded latency, identical tokens.

Counters live on the process-global ``STATS`` and render on /metrics
(frontend/service.py) as ``llm_kv_integrity_*``.
"""
from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict

import numpy as np
import xxhash


def page_checksum(*arrays) -> int:
    """xxh3-64 over the concatenated raw bytes of one page's arrays
    (k then v). Computed at capture; verified at every fetch."""
    h = xxhash.xxh3_64(seed=0)
    for a in arrays:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.intdigest()


class IntegrityError(ValueError):
    """A fetched page's bytes do not match its capture-time checksum."""

    def __init__(self, where: str, pages):
        self.where = where
        self.pages = list(pages)
        super().__init__(
            f"kv integrity mismatch at {where}: page(s) {self.pages}")


@dataclasses.dataclass
class IntegrityStats:
    """Process-global counters (/metrics: llm_kv_integrity_*)."""

    pages_hashed: int = 0      # checksums computed at capture
    pages_verified: int = 0    # fetch-time verifications that passed
    mismatches: int = 0        # fetch-time verifications that failed
    refetches: int = 0         # transfer retries triggered by a mismatch
    quarantined: int = 0       # tier entries dropped on verify failure
    reprefills: int = 0        # remote paths abandoned for local recompute

    def snapshot(self) -> Dict[str, int]:
        return dataclasses.asdict(self)

    def reset(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, 0)


STATS = IntegrityStats()


@dataclasses.dataclass
class KvTransferStats:
    """Process-global KV-transfer volume counters (/metrics:
    llm_kv_transfer_*). Bytes count the WIRE representation — for
    kv_quant="int8" engines that is the quantized int8 pages plus their
    f32 scale rows, so bytes_sent / fetches is the honest
    bytes-per-fetch figure the capacity math relies on (~2x below a
    bf16 engine's at the same page count)."""

    bytes_sent: int = 0       # payload bytes shipped by transfer senders
    pages_sent: int = 0       # pages those bytes carried
    fetches: int = 0          # transfer frames fetched/injected
    bytes_fetched: int = 0    # payload bytes arriving at inject
    # chunk-committed streaming (disagg/remote_transfer.py): transfers
    # that resumed from a non-zero committed frontier instead of
    # restarting (mid-stream link failure OR a replacement sender after
    # queue re-lease), pages a decode-side salvage re-used from the
    # committed prefix instead of re-prefilling, chunks rejected by the
    # (request_id, alloc_epoch) fence (a stale sender writing after the
    # pages were reallocated), and per-IO socket timeouts treated as
    # link death
    resumes: int = 0
    salvaged_pages: int = 0
    stale_chunks: int = 0
    link_timeouts: int = 0
    # sharded parallel transfer (disagg/remote_transfer.py): sends that
    # fanned out over N (shard, host) chunk-committed streams
    parallel_transfers: int = 0

    # per-(shard, host) stream dimension, keyed by the canonical
    # "{engine}/{host}#{stream}" key (remote_transfer.stream_key):
    # sender-side unique bytes/pages + chunk-level resumes, receiver-
    # side last committed frontier. Rendered as labeled gauges
    # (llm_kv_transfer_stream_*) next to the scalar family; bounded —
    # a fleet's stream-key population is (engines x hosts x shards).
    MAX_STREAM_KEYS = 256

    def __post_init__(self):
        self.per_stream: "OrderedDict[str, Dict[str, int]]" = OrderedDict()

    def note_stream(self, key: str, *, bytes: int = 0, pages: int = 0,
                    resumes: int = 0, frontier: int = -1) -> None:
        row = self.per_stream.get(key)
        if row is None:
            row = self.per_stream[key] = {
                "bytes": 0, "pages": 0, "resumes": 0, "frontier": 0}
            while len(self.per_stream) > self.MAX_STREAM_KEYS:
                self.per_stream.popitem(last=False)
        else:
            self.per_stream.move_to_end(key)
        row["bytes"] += bytes
        row["pages"] += pages
        row["resumes"] += resumes
        if frontier >= 0:
            row["frontier"] = frontier

    def stream_snapshot(self) -> Dict[str, Dict[str, int]]:
        return {k: dict(v) for k, v in self.per_stream.items()}

    def snapshot(self) -> Dict[str, int]:
        return {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)}

    def reset(self) -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name, 0)
        self.per_stream.clear()


XFER_STATS = KvTransferStats()
