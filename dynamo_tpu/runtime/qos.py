"""Multi-tenant QoS: priority classes, weighted-fair sharing, preemption
policy (ROADMAP item 5).

Serving millions of users means CONTENTION, not just scale: before this
module, admission was FIFO behind one global 429 knob, the leased
prefill queue served strictly FIFO, and the engine's only preemption
policy was youngest-first — one tenant's batch burst starved every
interactive user at every layer. This module is the shared vocabulary
the whole stack speaks instead:

- **QosClass / QosPolicy** — the class table (name, priority, weight,
  rate/concurrency budgets, TTFT/ITL targets, preemption budget). The
  default three-tier table (`interactive` / `standard` / `batch`)
  mirrors the classic latency/throughput split; deployments replace it
  wholesale via `QosPolicy(classes=...)`.
- **Baggage carriage** — the class name rides `Context.baggage[QOS_KEY]`
  exactly the way the PR-8 trace context rides `baggage["trace"]`: the
  dispatch envelope ships baggage verbatim over every wire hop
  (runtime/component.py), so admission, routing, the leased prefill
  queue, and the engine scheduler all see the SAME class without any
  protocol surgery.
- **StridePicker** — deterministic weighted-fair ordering (stride
  scheduling: each service advances a class's virtual pass by
  K/weight; the next pick is the backlogged class with the smallest
  pass) with a BOUNDED-AGING no-starvation guarantee: a backlogged
  class skipped `aging_limit` consecutive picks is served next
  regardless of pass values, and the promotion is counted
  (`aging_promotions` — the storm contract's starvation evidence).
- **AdmissionState** — the synchronous core of weighted-fair admission
  (per-class token-bucket rate budgets, optional per-class concurrency
  caps, class-aware shed with batch-first displacement, Retry-After
  scaled by the shedder's class queue depth). The async
  `frontend/reliability.AdmissionControl` wraps it with futures; the
  QoS storm (tools/fleet_storm.py --mode qos) drives it directly on a
  virtual clock, so the committed decision timeline exercises the REAL
  admission logic.
- **select_victim** — the engine scheduler's policy-driven preemption
  victim: lowest QoS priority first, youngest (fewest computed tokens)
  within a class, so same-class pressure keeps the historical
  youngest-first behavior bit-for-bit. Cross-class preemption is
  charged against the preemptor's class `preempt_budget` (outstanding
  debt, repaid when the victim resumes), and victims re-enter the
  waiting queue at the head of their class band — together with the
  queue's bounded aging this bounds how long a batch victim can starve
  (docs/RESILIENCE.md "Multi-tenant QoS").

Pure stdlib + dataclasses on purpose: the engine scheduler, the disagg
queue, the frontend, and the router all import this module, so it must
sit below all of them in the dependency order.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# Context.baggage key the class name rides under (the TRACE_KEY twin)
QOS_KEY = "qos"

# stride constant: pass increments are STRIDE_K / weight, so integer-ish
# weights keep ratios exact in float arithmetic at any realistic scale
STRIDE_K = 10_000.0


@dataclasses.dataclass(frozen=True)
class QosClass:
    """One tenant class and its budgets/targets.

    `priority` orders classes for preemption and queue bypass (higher
    preempts lower); `weight` sets the weighted-fair service share
    (admission grants + prefill-queue dequeues); `rate_per_s`/`burst`
    are the admission token bucket (0 = unlimited); `max_concurrency`
    caps simultaneously admitted requests of this class (0 = no cap);
    `ttft_target_s`/`itl_target_s` feed the per-class SloSpecs the
    watchdog pages on; `preempt_budget` bounds OUTSTANDING cross-class
    preemptions this class may cause (debt repaid when a victim
    resumes — 0 means the class may never preempt anyone);
    `latency_weight` scales the router's transfer/backlog cost term
    (latency-sensitive classes avoid backlogged links first)."""

    name: str
    priority: int
    weight: float = 1.0
    rate_per_s: float = 0.0
    burst: float = 0.0
    max_concurrency: int = 0
    ttft_target_s: float = 2.0
    itl_target_s: float = 0.25
    preempt_budget: int = 0
    latency_weight: float = 1.0


DEFAULT_CLASSES: Tuple[QosClass, ...] = (
    QosClass("interactive", priority=2, weight=8.0, ttft_target_s=0.5,
             itl_target_s=0.1, preempt_budget=4, latency_weight=2.0),
    QosClass("standard", priority=1, weight=3.0, ttft_target_s=2.0,
             itl_target_s=0.25, preempt_budget=1, latency_weight=1.0),
    # batch TTFT target sits INSIDE the serving histogram's bucket
    # ladder (top finite bound 30s): the SLO evaluator's bucket
    # quantile cannot exceed the largest finite bound, so a target AT
    # the top could never fire (observability/metrics.Histogram)
    QosClass("batch", priority=0, weight=1.0, ttft_target_s=20.0,
             itl_target_s=1.0, preempt_budget=0, latency_weight=0.5),
)


class QosPolicy:
    """The class table + the bounds every consumer shares.

    `aging_limit` is THE no-starvation bound (dynalint R19): any
    weighted-fair or priority-ordered consumer (admission grants,
    prefill-queue dequeue, scheduler queue bypass) may skip a
    backlogged lower class at most `aging_limit` consecutive times
    before it MUST be served/pinned. Unknown class names resolve to
    `default` — a misconfigured client degrades to standard service,
    never to an error or to accidental priority."""

    def __init__(self, classes: Sequence[QosClass] = DEFAULT_CLASSES,
                 default: str = "standard", aging_limit: int = 16):
        if not classes:
            raise ValueError("QosPolicy needs at least one class")
        self.classes: Dict[str, QosClass] = {c.name: c for c in classes}
        if default not in self.classes:
            default = next(iter(self.classes))
        self.default = default
        if aging_limit < 1:
            raise ValueError("aging_limit must be >= 1")
        self.aging_limit = aging_limit

    def resolve(self, name: Optional[str]) -> QosClass:
        return self.classes.get(name or "", self.classes[self.default])

    def names(self) -> List[str]:
        return sorted(self.classes,
                      key=lambda n: -self.classes[n].priority)

    def priority_of(self, name: Optional[str]) -> int:
        return self.resolve(name).priority


DEFAULT_POLICY = QosPolicy()


# -- baggage carriage ----------------------------------------------------------


def qos_of(baggage: Optional[dict]) -> str:
    """Class name riding the request baggage ('' when unclassed)."""
    if not baggage:
        return ""
    v = baggage.get(QOS_KEY)
    return v if isinstance(v, str) else ""


def qos_label(baggage: Optional[dict],
              policy: Optional[QosPolicy] = None) -> str:
    """Metrics label for the request's class: the resolved class name
    (unknown/unclassed requests label as the policy default, so the
    per-class histograms partition every request exactly once)."""
    return (policy or DEFAULT_POLICY).resolve(qos_of(baggage)).name


def set_qos(baggage: dict, name: str) -> dict:
    baggage[QOS_KEY] = name
    return baggage


# -- weighted-fair ordering with bounded aging ---------------------------------


class StridePicker:
    """Deterministic weighted-fair class ordering (stride scheduling)
    with the policy's bounded-aging no-starvation guarantee.

    Service ratios converge to the class weight ratios; a backlogged
    class skipped `aging_limit` consecutive `charge()` rounds jumps the
    order regardless of its pass value (`aging_promotions` counts the
    jumps — the storm's "batch not starved" evidence). Pure state
    machine: no clocks, no randomness — replay-identical."""

    def __init__(self, policy: QosPolicy):
        self.policy = policy
        self._pass: Dict[str, float] = {}
        self._skipped: Dict[str, int] = {}
        self.aging_promotions = 0
        self.served: Dict[str, int] = {}

    def _stride(self, cls: str) -> float:
        return STRIDE_K / max(1e-6, self.policy.resolve(cls).weight)

    def order(self, backlogged: Iterable[str]) -> List[str]:
        """Service order over the currently-backlogged classes: aged
        classes first (no-starvation), then ascending virtual pass,
        priority then name as deterministic tie-breaks."""
        classes = [c for c in backlogged]
        if not classes:
            return []
        base = min(self._pass.values()) if self._pass else 0.0
        for c in classes:
            # a newly-backlogged class starts at the current floor, so
            # an idle class can't bank unbounded credit and then burst
            self._pass.setdefault(c, base)
            self._pass[c] = max(self._pass[c], base)
            self._skipped.setdefault(c, 0)
        aged = [c for c in classes
                if self._skipped[c] >= self.policy.aging_limit]

        def key(c: str):
            return (self._pass[c], -self.policy.priority_of(c), c)

        rest = sorted((c for c in classes if c not in aged), key=key)
        return sorted(aged, key=key) + rest

    def charge(self, served: str,
               backlogged: Iterable[str] = ()) -> None:
        """Account one service of `served`; every OTHER backlogged
        class's skip counter advances (the aging clock)."""
        if self._skipped.get(served, 0) >= self.policy.aging_limit:
            self.aging_promotions += 1
        self._pass[served] = self._pass.get(served, 0.0) \
            + self._stride(served)
        self._skipped[served] = 0
        self.served[served] = self.served.get(served, 0) + 1
        for c in backlogged:
            if c != served:
                self._skipped[c] = self._skipped.get(c, 0) + 1


# -- admission core ------------------------------------------------------------


class TokenBucket:
    """Per-class admission rate budget (clock-injectable)."""

    def __init__(self, rate_per_s: float, burst: float):
        self.rate = max(0.0, rate_per_s)
        self.burst = max(burst, self.rate) if self.rate else 0.0
        self._tokens = self.burst
        self._last: Optional[float] = None

    def take(self, now: float, n: float = 1.0) -> bool:
        if self.rate <= 0.0:
            return True       # unlimited
        if self._last is None:
            self._last = now
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False


@dataclasses.dataclass
class AdmissionDecision:
    """One try_admit outcome. kind: "admit" | "queue" | "shed" |
    "displace" (shed the newest queued request of `victim_class` —
    always the lowest-priority backlogged class, so batch sheds first —
    then queue the arrival)."""

    kind: str
    retry_after_s: int = 0
    victim_class: str = ""


class AdmissionState:
    """Synchronous core of weighted-fair admission.

    Work-conserving: any class may use free inflight slots (a lone
    batch tenant gets the whole box); fairness bites only under
    contention — freed slots grant to queued classes in StridePicker
    order (weighted-fair + bounded aging), over-cap arrivals shed the
    LOWEST-priority queued work first (displacement), and each class's
    token-bucket rate budget and optional concurrency cap bound what
    it can claim at all. Retry-After scales with the shedder's own
    class queue depth (a deep batch backlog tells batch clients to
    back off longer; it says nothing to interactive clients).

    Clock-injectable and future-free: the async AdmissionControl
    manages waiter futures; the QoS storm drives this directly."""

    def __init__(self, policy: QosPolicy, max_inflight: int,
                 max_queued: int = 0, retry_after_s: int = 1):
        self.policy = policy
        self.max_inflight = max_inflight
        self.max_queued = max_queued
        self.retry_after_s = retry_after_s
        self.picker = StridePicker(policy)
        self.active: Dict[str, int] = {}
        self.queued: Dict[str, int] = {}
        self.buckets: Dict[str, TokenBucket] = {
            name: TokenBucket(c.rate_per_s, c.burst)
            for name, c in policy.classes.items()}
        self.displaced = 0

    # -- helpers --------------------------------------------------------------

    def _cls(self, name: Optional[str]) -> QosClass:
        return self.policy.resolve(name)

    def active_total(self) -> int:
        return sum(self.active.values())

    def queued_total(self) -> int:
        return sum(self.queued.values())

    def retry_after(self, cls_name: str) -> int:
        """Class-aware Retry-After: base scaled by the shedder's OWN
        class queue depth (ISSUE 14 satellite — a constant hint made
        every shed client retry into the same wall)."""
        depth = self.queued.get(cls_name, 0)
        return max(1, int(self.retry_after_s * (1 + depth)))

    # -- transitions ----------------------------------------------------------

    def try_admit(self, qos: Optional[str], now: float
                  ) -> AdmissionDecision:
        c = self._cls(qos)
        if not self.buckets[c.name].take(now):
            # over the class rate budget: shed THIS request, whatever
            # its priority — budgets are the inter-tenant contract
            return AdmissionDecision("shed",
                                     retry_after_s=self.retry_after(c.name))
        over_cap = (c.max_concurrency
                    and self.active.get(c.name, 0) >= c.max_concurrency)
        if self.active_total() < self.max_inflight and not over_cap:
            self.active[c.name] = self.active.get(c.name, 0) + 1
            return AdmissionDecision("admit")
        if self.queued_total() < self.max_queued:
            self.queued[c.name] = self.queued.get(c.name, 0) + 1
            return AdmissionDecision("queue")
        # queue full: batch-class work sheds FIRST — displace the
        # newest queued request of the lowest-priority backlogged
        # class when the arrival outranks it; otherwise shed self
        victim = self._displacement_victim(c)
        if victim is not None:
            self.displaced += 1
            self.queued[victim] -= 1
            if not self.queued[victim]:
                del self.queued[victim]
            self.queued[c.name] = self.queued.get(c.name, 0) + 1
            return AdmissionDecision("displace", victim_class=victim,
                                     retry_after_s=self.retry_after(victim))
        return AdmissionDecision("shed",
                                 retry_after_s=self.retry_after(c.name))

    def _displacement_victim(self, arriving: QosClass) -> Optional[str]:
        lowest: Optional[str] = None
        for name, n in self.queued.items():
            if n <= 0:
                continue
            if lowest is None or (self.policy.priority_of(name)
                                  < self.policy.priority_of(lowest)):
                lowest = name
        if lowest is not None \
                and self.policy.priority_of(lowest) < arriving.priority:
            return lowest
        return None

    def grant(self) -> Optional[str]:
        """A slot freed: which queued class runs next? Weighted-fair
        with the bounded-aging guarantee (StridePicker.order); the
        caller moves one waiter of the returned class to active via
        note_granted()."""
        backlogged = [n for n, v in self.queued.items() if v > 0]
        order = self.picker.order(backlogged)
        if not order:
            return None
        cls = order[0]
        self.picker.charge(cls, backlogged)
        return cls

    def note_granted(self, cls_name: str) -> None:
        self.queued[cls_name] -= 1
        if not self.queued[cls_name]:
            del self.queued[cls_name]
        self.active[cls_name] = self.active.get(cls_name, 0) + 1

    def note_abandoned(self, cls_name: str) -> None:
        """A queued waiter gave up (timeout / displaced / cancelled)."""
        n = self.queued.get(cls_name, 0)
        if n > 1:
            self.queued[cls_name] = n - 1
        else:
            self.queued.pop(cls_name, None)

    def note_released(self, cls_name: str) -> None:
        n = self.active.get(cls_name, 0)
        if n > 1:
            self.active[cls_name] = n - 1
        else:
            self.active.pop(cls_name, None)


# -- engine preemption policy --------------------------------------------------


def seq_priority(seq, policy: QosPolicy = DEFAULT_POLICY) -> int:
    """QoS priority of a scheduler sequence (unclassed sequences rank
    at the policy default, so a class-free deployment keeps today's
    single-band youngest-first behavior everywhere)."""
    return policy.priority_of(getattr(seq, "qos", "") or None)


def select_victim(running: Iterable, policy: QosPolicy = DEFAULT_POLICY,
                  below_prio: Optional[int] = None):
    """Policy-driven preemption victim: the LOWEST-QoS-priority running
    sequence, youngest (fewest computed tokens) within that class — so
    same-class pressure keeps the historical youngest-first pick
    bit-for-bit. `below_prio` restricts candidates to classes strictly
    below it (cross-class preemption only; None = any victim, the
    memory-pressure fallback).

    No-starvation: victims requeue at the head of their class band and
    the waiting queue's bypass counter is bounded by
    `QosPolicy.aging_limit`, so a preempted batch request is skipped at
    most aging_limit times before it pins to the front (dynalint R19);
    cross-class preemptions are additionally bounded by the
    preemptor's class `preempt_budget`."""
    victim = None
    vkey = None
    for seq in running:
        if seq is None:
            continue
        prio = seq_priority(seq, policy)
        if below_prio is not None and prio >= below_prio:
            continue
        key = (prio, seq.num_computed)
        if vkey is None or key < vkey:
            victim, vkey = seq, key
    return victim


# -- process-global stats (render-time /metrics fold) --------------------------


class QosStats:
    """Process-global QoS counters, folded into llm_qos_* gauges at
    /metrics render time (the XFER_STATS pattern). Scalars in FIELDS;
    the per-class dicts fold into labeled gauges."""

    FIELDS = ("preemptions_total", "preempt_denied_budget",
              "sched_bypasses", "sched_aging_pins",
              "queue_aging_promotions", "admission_displaced",
              "admission_aging_promotions")

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.preemptions_total = 0       # cross-class scheduler preempts
        self.preempt_denied_budget = 0   # refused: class debt exhausted
        self.sched_bypasses = 0          # waiting-queue class bypasses
        self.sched_aging_pins = 0        # seqs pinned by the aging bound
        self.queue_aging_promotions = 0  # prefill-queue aging services
        self.admission_displaced = 0     # batch-first queue displacement
        self.admission_aging_promotions = 0
        self.shed_by_class: Dict[str, int] = {}
        self.preempt_by_class: Dict[str, int] = {}   # preemptOR class
        self.preempted_by_class: Dict[str, int] = {}  # victim class

    def snapshot(self) -> Dict[str, float]:
        return {name: float(getattr(self, name)) for name in self.FIELDS}

    def note_shed(self, cls_name: str) -> None:
        self.shed_by_class[cls_name] = \
            self.shed_by_class.get(cls_name, 0) + 1

    def note_preempt(self, preemptor_cls: str, victim_cls: str) -> None:
        self.preemptions_total += 1
        self.preempt_by_class[preemptor_cls] = \
            self.preempt_by_class.get(preemptor_cls, 0) + 1
        self.preempted_by_class[victim_cls] = \
            self.preempted_by_class.get(victim_cls, 0) + 1


QOS_STATS = QosStats()


# -- misc ----------------------------------------------------------------------


def now_monotonic() -> float:
    return time.monotonic()
