"""Response-plane transport: direct call-home streams (TCP, or UDS same-host).

Like the reference, responses never transit the message broker: the requester
registers a pending stream on its local server and sends its address with
the request; the responder dials back ("call home"), sends a prologue
(ok/error), then pumps response frames (reference:
lib/runtime/src/pipeline/network/tcp/server.rs:74-380, tcp/client.rs:77-130,
egress/push.rs:104-166). The connection is bidirectional: the requester can
send a {"stop": true} control frame to cancel generation mid-stream, and a
dropped connection stops the responder's engine (the reference's
monitor_for_disconnects / context kill path).

Alternative same-host plane (the reference's ZMQ/IPC data-plane option,
SURVEY.md §2.1): alongside TCP the server also listens on a unix-domain
socket and advertises its path; a responder on the SAME machine (the path
exists locally) dials the UDS instead — kernel-local streams with no TCP
stack in the hot loop — and falls back to TCP on any UDS failure.
`DYN_DATAPLANE=tcp` disables the UDS listener entirely.
"""
from __future__ import annotations

import asyncio
import logging
import os
import tempfile
import uuid
from typing import AsyncIterator, Dict, Optional, Tuple

from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.wire import read_frame, write_frame

log = logging.getLogger("dynamo_tpu.dataplane")

_END = object()

# Responder emits a keepalive frame whenever the engine takes longer than
# this between output items; the requester's inactivity timeout (below) only
# fires after several missed keepalives, i.e. when the peer is actually gone
# — not merely slow (a giant prefill before the first token is legitimate;
# VERDICT r2 weak #8).
KEEPALIVE_INTERVAL_S = 15.0
INACTIVITY_TIMEOUT_S = 60.0
# call-home dial bound: a requester that vanished between dispatch and
# dial-back must cost seconds, not the OS connect timeout's minutes
CONNECT_TIMEOUT_S = 10.0


def _uds_enabled() -> bool:
    """One policy switch for both ends: the server's UDS listener and the
    responder's UDS dial (DYN_DATAPLANE=tcp disables both)."""
    return os.environ.get("DYN_DATAPLANE", "auto") != "tcp"


class StreamInactiveError(RuntimeError):
    """Typed dead-stream signal: no frames (not even keepalives) arrived
    within the inactivity window — the responder process is gone or wedged,
    as opposed to backpressured/slow."""


class PendingStream:
    def __init__(self, stream_id: str):
        self.stream_id = stream_id
        self.queue: asyncio.Queue = asyncio.Queue()
        self.writer: Optional[asyncio.StreamWriter] = None
        self.connected = asyncio.Event()


class DataPlaneServer:
    """Per-process TCP server accepting call-home response connections."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 advertise_host: Optional[str] = None,
                 uds: Optional[bool] = None):
        self.host, self.port = host, port
        self.advertise_host = advertise_host or host
        self._pending: Dict[str, PendingStream] = {}
        self._server = None
        # same-host UDS listener (advertised alongside TCP); default on,
        # DYN_DATAPLANE=tcp turns it off
        if uds is None:
            uds = _uds_enabled()
        self._want_uds = uds
        self._uds_server = None
        self.uds_path: Optional[str] = None
        self.uds_accepts = 0  # observability: streams that arrived via UDS

    async def start(self):
        if self._server is None:
            self._server = await asyncio.start_server(
                self._on_connect, self.host, self.port)
            self.port = self._server.sockets[0].getsockname()[1]
        if self._want_uds and self._uds_server is None:
            path = os.path.join(
                tempfile.gettempdir(),
                f"dynamo-dp-{os.getpid()}-{uuid.uuid4().hex[:8]}.sock")
            try:
                self._uds_server = await asyncio.start_unix_server(
                    self._on_uds_connect, path)
                self.uds_path = path
            except (OSError, NotImplementedError):  # pragma: no cover
                log.warning("UDS data plane unavailable; TCP only")
        return self

    async def stop(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        if self._uds_server:
            self._uds_server.close()
            await self._uds_server.wait_closed()
            self._uds_server = None
        if self.uds_path:
            try:
                os.unlink(self.uds_path)
            except OSError:
                pass
            self.uds_path = None

    async def _on_uds_connect(self, reader, writer):
        self.uds_accepts += 1
        await self._on_connect(reader, writer)

    @property
    def connection_info(self) -> Dict[str, object]:
        info: Dict[str, object] = {"host": self.advertise_host,
                                   "port": self.port}
        if self.uds_path:
            info["uds"] = self.uds_path
        return info

    def register(self) -> PendingStream:
        stream = PendingStream(uuid.uuid4().hex)
        self._pending[stream.stream_id] = stream
        return stream

    def unregister(self, stream_id: str) -> None:
        self._pending.pop(stream_id, None)

    async def _on_connect(self, reader, writer):
        stream = None
        try:
            hello = await read_frame(reader)  # CallHomeHandshake
            stream = self._pending.get(hello.get("stream_id", ""))
            if stream is None:
                write_frame(writer, {"ok": False, "error": "unknown stream"})
                await writer.drain()
                writer.close()
                return
            stream.writer = writer
            stream.connected.set()
            write_frame(writer, {"ok": True})
            await writer.drain()
            while True:
                frame = await read_frame(reader)
                stream.queue.put_nowait(frame)
                if frame.get("end"):
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError):
            if stream is not None:
                stream.queue.put_nowait(
                    {"end": True, "error": "response stream lost"})
        finally:
            if stream is not None:
                self._pending.pop(stream.stream_id, None)
            writer.close()

    async def stream_responses(
            self, stream: PendingStream,
            timeout: Optional[float] = None) -> AsyncIterator[bytes]:
        """Yield response payload frames until end; raises on stream error.

        Keepalive frames reset the inactivity timer without being yielded,
        so a slow-but-alive responder (long prefill, deep queue) is never
        killed; a truly dead peer raises StreamInactiveError after
        `timeout` seconds of total silence. timeout=None reads the module
        constant at call time so deployments can tune it.
        """
        if timeout is None:
            timeout = INACTIVITY_TIMEOUT_S
        try:
            while True:
                try:
                    frame = await asyncio.wait_for(stream.queue.get(), timeout)
                except asyncio.TimeoutError:
                    raise StreamInactiveError(
                        f"no response frames for {timeout:.0f}s "
                        f"(responder dead or unreachable)") from None
                if frame.get("keepalive"):
                    continue
                if frame.get("error"):
                    raise RuntimeError(frame["error"])
                if "data" in frame and frame["data"] is not None:
                    yield frame["data"]
                if frame.get("end"):
                    return
        finally:
            self.unregister(stream.stream_id)
            if stream.writer is not None:
                stream.writer.close()

    async def send_stop(self, stream: PendingStream) -> None:
        """Cancel generation: send a stop control frame back to the responder."""
        if stream.writer is not None and not stream.writer.is_closing():
            write_frame(stream.writer, {"stop": True})
            await stream.writer.drain()


async def call_home(
    connection_info: Dict[str, object],
    stream_id: str,
    context: Context,
) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    """Responder side: dial the requester and complete the handshake.

    Prefers the requester's advertised unix socket when its path exists
    on THIS machine (same-host fast path; falls back to TCP on any UDS
    failure — the path existing doesn't prove it is the same requester,
    e.g. after a host reboot reused a pid). Also spawns a reader task
    that maps incoming {"stop": true} frames and connection loss onto
    the request Context.
    """
    reader = writer = None
    uds = connection_info.get("uds")
    if uds and os.path.exists(uds) and _uds_enabled():
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_unix_connection(uds), CONNECT_TIMEOUT_S)
        except (OSError, NotImplementedError, asyncio.TimeoutError):
            reader = writer = None
    if reader is None:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(
                connection_info["host"], int(connection_info["port"])),
            CONNECT_TIMEOUT_S)
    write_frame(writer, {"stream_id": stream_id})
    await writer.drain()
    ack = await read_frame(reader)
    if not ack.get("ok"):
        writer.close()
        raise ConnectionError(ack.get("error", "handshake rejected"))

    async def watch_control():
        try:
            while True:
                frame = await read_frame(reader)
                if frame.get("stop"):
                    context.stop_generating()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.CancelledError):
            context.stop_generating()

    task = asyncio.create_task(watch_control())
    writer._dynamo_watch_task = task  # cancelled when stream finishes
    return reader, writer


async def close_with_error(writer: asyncio.StreamWriter, message: str) -> None:
    """Responder side: report a failure and tear the stream down."""
    try:
        write_frame(writer, {"end": True, "error": message})
        await writer.drain()
    except (ConnectionResetError, BrokenPipeError):
        pass
    finally:
        task = getattr(writer, "_dynamo_watch_task", None)
        if task:
            task.cancel()
        writer.close()


async def _next_with_keepalive(writer: asyncio.StreamWriter, it):
    """Await the next engine item, emitting a keepalive frame every
    KEEPALIVE_INTERVAL_S while the engine is silent. Returns (_END, None)
    on exhaustion."""
    nxt = asyncio.ensure_future(it.__anext__())
    while True:
        try:
            return await asyncio.wait_for(
                asyncio.shield(nxt), KEEPALIVE_INTERVAL_S)
        except asyncio.CancelledError:
            # handler teardown: propagate cancellation into the engine
            # generator like the old `async for` did, instead of leaving
            # the shielded __anext__ running detached
            nxt.cancel()
            raise
        except asyncio.TimeoutError:
            try:
                write_frame(writer, {"keepalive": True})
                await writer.drain()
            except Exception:
                # requester is gone: don't orphan the in-flight engine step
                nxt.cancel()
                raise
        except StopAsyncIteration:
            return _END


async def pump_stream(writer: asyncio.StreamWriter, gen,
                      context: Context) -> None:
    """Responder side: forward engine output frames into the TCP socket."""
    try:
        it = gen.__aiter__()
        while True:
            item = await _next_with_keepalive(writer, it)
            if item is _END or context.is_killed:
                break
            write_frame(writer, {"data": item})
            await writer.drain()
        write_frame(writer, {"end": True})
        await writer.drain()
    except (ConnectionResetError, BrokenPipeError):
        context.stop_generating()
    except Exception as e:  # noqa: BLE001 — forwarded to the requester
        try:
            write_frame(writer, {"end": True,
                                 "error": f"{type(e).__name__}: {e}"})
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
    finally:
        task = getattr(writer, "_dynamo_watch_task", None)
        if task:
            task.cancel()
        writer.close()
