"""Deterministic fault injection: a failpoint registry for the data plane.

SURVEY.md §5 notes the reference ships no fault-injection framework; our
chaos harness (tests/test_chaos.py) grew one out of ad-hoc monkeypatching
and a jittery latency model. This module replaces that with *named
failpoint sites* armed by *seeded schedules*, so every failure mode is a
replayable artifact: the same seed fires the same faults in the same
order, and a recorded schedule JSON re-runs byte-for-byte through
tools/chaos_replay.py.

Sites (the catalog lives in docs/RESILIENCE.md):

    transport.send              control-plane op leaving this process
    transport.recv              event/frame delivery into a subscriber
    remote_transfer.fetch_page  KV page bytes crossing the transfer plane
    transfer.link               the data-plane link itself, fired once
                                per streamed KV chunk on the sender: a
                                drop is a link cut / connection reset
                                mid-transfer (the sender must RESUME
                                from the committed frontier, not
                                restart), a delay is a stalled socket
                                (the per-IO timeouts must bound it);
                                `skip` pins the fault to a seeded chunk
                                index
    offload.write_tier          KV page landing in a host/disk tier slab
    offload.read_tier           KV page read back out of a tier slab
    queue.dequeue               durable work-queue consumption
    discovery.heartbeat         lease keep-alive ticks

Control-plane sites (the 1000-worker sim harness, runtime/simcluster.py,
drives churn storms through these; PR 4 added the 7 data-plane sites
above):

    watch.stream                watch-event delivery into a watcher; a
                                drop raises into the consumer's pump —
                                the watch-stream-disconnect model (the
                                pump must resume + resync, not die)
    discovery.store             discovery-store op (get/put/delete/
                                get_prefix) during an unavailable window
                                — the etcd-quorum-loss model
    lease.expiry                lease watchdog tick; a drop force-expires
                                the lease NOW (seeded p over a fleet =
                                a lease-expiry burst)
    event.plane                 per-subscriber event delivery; delay is
                                applied ASYNCHRONOUSLY (call_later), so
                                delayed events arrive late AND out of
                                order — the event-plane lag/reorder
                                model; drop loses the event, duplicate
                                doubles it

Fault kinds: ``drop`` (the op raises FaultInjected, a ConnectionError —
the recovery layers treat it as any transport death), ``delay`` (seeded
jitter up to delay_s), ``corrupt`` (flip nbytes seeded byte positions in
the payload), ``duplicate`` (the site delivers twice), ``fail_n``
(deterministically fail the first n hits, then pass — the shape that
proves bounded retries actually bound), and ``slow`` (a PERSISTENT
degradation: every hit the rule fires on reports a multiplicative
``slow_factor`` the site applies to its own base duration — the
gray-failure / fail-slow model, distinct from one-shot ``delay`` jitter;
a worker armed with factor=10 is 10x slow for the whole run, which is
what the fail-slow detection plane has to catch).

Zero-cost when disarmed: call sites guard with ``if REGISTRY.enabled:``
— one attribute read on the hot path, no coroutine, no rng draw.
Determinism: each armed site owns one ``random.Random(seed)``; every hit
consumes a fixed number of draws per spec regardless of outcome, so the
decision sequence is a pure function of (seed, specs, hit index).
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Dict, List, Optional, Sequence

SITES = (
    "transport.send",
    "transport.recv",
    "remote_transfer.fetch_page",
    "transfer.link",
    "offload.write_tier",
    "offload.read_tier",
    "pool.fetch",
    "pool.remote_fetch",
    "pool.rebalance",
    "queue.dequeue",
    "discovery.heartbeat",
    # control-plane sites (this PR's scale harness)
    "watch.stream",
    "discovery.store",
    "lease.expiry",
    "event.plane",
)

KINDS = ("drop", "delay", "corrupt", "duplicate", "fail_n", "slow")


class FaultInjected(ConnectionError):
    """Raised at a site for drop/fail_n outcomes. A ConnectionError
    subclass on purpose: every recovery layer (reliability migration,
    transfer reconnect, queue redelivery) already treats connection
    death as survivable — injected faults must ride the same paths."""

    def __init__(self, site: str):
        super().__init__(f"fault injected at {site}")
        self.site = site


@dataclasses.dataclass
class FaultSpec:
    """One rule inside a schedule. ``p`` is the per-hit probability
    (seeded); ``n`` bounds how many hits the rule may fire on in total
    (0 = unbounded) — `fail_n` uses it as the fail-then-ok budget, and
    a `corrupt` with n=1 models a transient single corruption that a
    bounded re-fetch must absorb. ``skip`` makes the rule dormant for
    the first `skip` hits, so a fault can be pinned to a deterministic
    hit index (a `fail_n` with skip=k, n=1 cuts exactly the k-th
    chunk/op — the transfer.link resume matrix rides this).
    ``delay_min_s`` floors the seeded delay draw (delay in
    [delay_min_s, delay_s]); delay_min_s == delay_s is a deterministic
    stall of exactly that length. ``factor`` is the `slow` kind's
    persistent multiplicative degradation (1.0 = healthy)."""

    kind: str
    p: float = 1.0
    n: int = 0
    delay_s: float = 0.0
    nbytes: int = 1
    skip: int = 0
    delay_min_s: float = 0.0
    factor: float = 1.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(expected one of {KINDS})")


@dataclasses.dataclass
class Outcome:
    """Merged per-hit decision a site acts on."""

    drop: bool = False
    delay_s: float = 0.0
    corrupt: bool = False
    duplicate: bool = False
    nbytes: int = 0
    slow_factor: float = 1.0

    @property
    def fired(self) -> bool:
        return self.drop or self.corrupt or self.duplicate \
            or self.delay_s > 0 or self.slow_factor != 1.0


class FaultSchedule:
    """Seeded decision stream for one site.

    Serializable (`to_dict`/`from_dict`) so a chaos run's exact fault
    plan is a recordable artifact. Decisions consume the rng in hit
    order; two schedules with equal (seed, specs) produce identical
    decision sequences — the replayability contract.
    """

    def __init__(self, seed: int, specs: Sequence[FaultSpec]):
        self.seed = int(seed)
        self.specs = [s if isinstance(s, FaultSpec) else FaultSpec(**s)
                      for s in specs]
        self._rng = random.Random(self.seed)
        self._fired: List[int] = [0] * len(self.specs)
        self.hits = 0

    def decide(self) -> Outcome:
        out = Outcome()
        self.hits += 1
        for i, spec in enumerate(self.specs):
            # one draw per spec per hit, unconditionally: outcomes never
            # shift the stream, so hit k's decision depends only on k
            roll = self._rng.random()
            if spec.n and self._fired[i] >= spec.n:
                continue
            if spec.skip and self.hits <= spec.skip:
                # dormant for the first `skip` hits: the roll above was
                # still consumed, so skipping never shifts the stream
                continue
            if spec.kind == "fail_n":
                # deterministic: fails exactly the first n (post-skip) hits
                self._fired[i] += 1
                out.drop = True
                continue
            if roll >= spec.p:
                continue
            self._fired[i] += 1
            if spec.kind == "drop":
                out.drop = True
            elif spec.kind == "delay":
                lo = min(spec.delay_min_s, spec.delay_s)
                out.delay_s = max(
                    out.delay_s,
                    lo + self._rng.random() * (spec.delay_s - lo))
            elif spec.kind == "corrupt":
                out.corrupt = True
                out.nbytes = max(out.nbytes, spec.nbytes)
            elif spec.kind == "duplicate":
                out.duplicate = True
            elif spec.kind == "slow":
                # persistent degradation: every firing hit reports the
                # same multiplicative factor — the call site applies it
                # to its own base duration, so a factor=10 worker is
                # 10x slow for as long as the rule stays armed
                out.slow_factor = max(out.slow_factor, spec.factor)
        return out

    def corrupt_positions(self, length: int, nbytes: int) -> List[int]:
        """Seeded byte offsets to flip for a corrupt outcome."""
        if length <= 0:
            return []
        return [self._rng.randrange(length)
                for _ in range(max(1, nbytes))]

    def reset(self) -> None:
        """Rewind to hit 0 (same seed -> same decisions again)."""
        self._rng = random.Random(self.seed)
        self._fired = [0] * len(self.specs)
        self.hits = 0

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "specs": [dataclasses.asdict(s) for s in self.specs]}

    @classmethod
    def from_dict(cls, d: dict) -> "FaultSchedule":
        return cls(d["seed"], [FaultSpec(**s) for s in d.get("specs", [])])


class FaultRegistry:
    """Site -> armed schedule, plus the counters /metrics surfaces.

    The module-level ``REGISTRY`` is the process-wide instance every
    instrumented call site consults; tests arm/disarm it around each
    scenario (see tests/test_faults.py's autouse fixture)."""

    def __init__(self):
        self._schedules: Dict[str, FaultSchedule] = {}
        self.enabled = False
        # observability: per-site hit and injected-fault counts
        # (frontend/service.py folds these into /metrics gauges)
        self.site_hits: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}

    # -- arming ---------------------------------------------------------------

    def arm(self, site: str, schedule: FaultSchedule) -> None:
        if site not in SITES:
            raise ValueError(f"unknown failpoint site {site!r} "
                             f"(expected one of {SITES})")
        self._schedules[site] = schedule
        self.enabled = True

    def arm_from_dict(self, plan: Dict[str, dict]) -> None:
        """Arm many sites from a recorded {site: schedule_dict} plan."""
        for site, sched in plan.items():
            self.arm(site, FaultSchedule.from_dict(sched))

    def to_dict(self) -> Dict[str, dict]:
        return {site: s.to_dict() for site, s in self._schedules.items()}

    def disarm(self, site: Optional[str] = None) -> None:
        if site is None:
            self._schedules.clear()
        else:
            self._schedules.pop(site, None)
        self.enabled = bool(self._schedules)

    def reset_counters(self) -> None:
        self.site_hits.clear()
        self.injected.clear()

    def armed(self, site: str) -> bool:
        return site in self._schedules

    # -- decision plumbing ----------------------------------------------------

    def _decide(self, site: str) -> Optional[Outcome]:
        sched = self._schedules.get(site)
        if sched is None:
            return None
        self.site_hits[site] = self.site_hits.get(site, 0) + 1
        out = sched.decide()
        if out.fired:
            self.injected[site] = self.injected.get(site, 0) + 1
        return out

    # -- site hooks -----------------------------------------------------------

    def decide(self, site: str) -> Optional[Outcome]:
        """Call-site-managed outcome: no sleep, no raise — the site
        applies drop/delay/duplicate itself (the event-plane delivery
        path uses this to schedule DELAYED puts instead of blocking the
        publisher, which is what makes injected lag also reorder)."""
        return self._decide(site)

    def slow_factor(self, site: str) -> float:
        """Persistent-degradation multiplier for sites that scale their
        own base duration by the `slow` kind (1.0 when disarmed). Counts
        as a hit: the decision stream stays a pure function of hit
        index, same as every other site hook."""
        out = self._decide(site)
        return 1.0 if out is None else out.slow_factor

    async def fire(self, site: str) -> Outcome:
        """Async sites: apply delay, raise on drop, return the outcome
        (sites that can duplicate inspect ``outcome.duplicate``)."""
        out = self._decide(site)
        if out is None:
            return Outcome()
        if out.delay_s > 0:
            import asyncio
            await asyncio.sleep(out.delay_s)
        if out.drop:
            raise FaultInjected(site)
        return out

    def fire_sync(self, site: str) -> Outcome:
        """Sync sites (engine/offload threads, lease bookkeeping):
        delay blocks the calling thread, drop raises."""
        out = self._decide(site)
        if out is None:
            return Outcome()
        if out.delay_s > 0:
            time.sleep(out.delay_s)
        if out.drop:
            raise FaultInjected(site)
        return out

    def corrupt_bytes(self, site: str, payload: bytes) -> bytes:
        """Byte-payload sites: seeded byte flips when the schedule says
        corrupt; drop raises; delay is ignored (wire sites pair this
        with an async fire on the framing path when delay matters)."""
        out = self._decide(site)
        if out is None or not out.corrupt:
            if out is not None and out.drop:
                raise FaultInjected(site)
            return payload
        sched = self._schedules[site]
        buf = bytearray(payload)
        for pos in sched.corrupt_positions(len(buf), out.nbytes):
            buf[pos] ^= 0xFF
        return bytes(buf)

    def corrupt_array(self, site: str, arr) -> bool:
        """ndarray sites (tier slabs): flip seeded bytes in place.
        Returns True when a corruption was injected."""
        out = self._decide(site)
        if out is None or not out.corrupt:
            return False
        import numpy as np
        flat = arr.reshape(-1).view(np.uint8)
        sched = self._schedules[site]
        for pos in sched.corrupt_positions(flat.shape[0], out.nbytes):
            flat[pos] ^= 0xFF
        return True

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        return {"hits": dict(self.site_hits),
                "injected": dict(self.injected)}


# the process-wide registry every instrumented site consults
REGISTRY = FaultRegistry()
