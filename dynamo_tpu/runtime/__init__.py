from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.distributed import DistributedRuntime
