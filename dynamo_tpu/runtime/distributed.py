"""DistributedRuntime: the per-process cluster handle.

Bundles the control-plane clients (KV + messaging), a worker id, the primary
lease (TTL 10s; lease lost => runtime shutdown, shutdown => lease revoked —
the same two-way coupling as the reference, reference:
lib/runtime/src/transports/etcd.rs:85-120), a lazily-started TCP data-plane
server for call-home response streams (reference:
lib/runtime/src/distributed.rs:110-120), and the component registry.
"""
from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Dict, List, Optional

from dynamo_tpu.runtime.component import Namespace
from dynamo_tpu.runtime.dataplane import DataPlaneServer
from dynamo_tpu.runtime.transports.base import KVStore, Lease, Messaging
from dynamo_tpu.runtime.transports.memory import MemoryPlane

log = logging.getLogger("dynamo_tpu.runtime")

# env-overridable like the reference's DYN_RUNTIME_* knobs (figment,
# reference lib/runtime/src/config.rs): heavily-loaded single-core hosts
# (CI) can starve the heartbeat task past TTL/3 and falsely expire leases
import os as _os

LEASE_TTL_S = float(_os.environ.get("DYN_LEASE_TTL_S", "10.0"))


class DistributedRuntime:
    def __init__(self, kv: KVStore, messaging: Messaging,
                 worker_id: Optional[str] = None,
                 advertise_host: str = "127.0.0.1"):
        self.kv = kv
        self.messaging = messaging
        self.worker_id = worker_id or uuid.uuid4().hex[:16]
        self.lease: Optional[Lease] = None
        self.shutdown_event = asyncio.Event()
        self._data_plane: Optional[DataPlaneServer] = None
        self._data_plane_lock = asyncio.Lock()
        self._served: List[object] = []
        self._advertise_host = advertise_host
        self._lease_watch: Optional[asyncio.Task] = None
        self._namespaces: Dict[str, Namespace] = {}

    # -- constructors --------------------------------------------------------

    @classmethod
    async def create_local(cls, plane: Optional[MemoryPlane] = None,
                           worker_id: Optional[str] = None
                           ) -> "DistributedRuntime":
        """In-process control plane (tests, single-process serving)."""
        plane = plane or MemoryPlane()
        rt = cls(plane.kv, plane.messaging, worker_id)
        rt._plane = plane
        await rt._init_lease()
        return rt

    @classmethod
    async def connect(cls, host: str = "127.0.0.1", port: int = 6230,
                      worker_id: Optional[str] = None,
                      advertise_host: str = "127.0.0.1",
                      addrs=None) -> "DistributedRuntime":
        """Connect to a standalone control-plane server.

        HA pairs: pass `addrs=[(h1, p1), (h2, p2)]` — or a comma list in
        `host` ("h1:p1,h2:p2", the DYN_COORD_ADDR form) — and the client
        follows whichever member is primary, riding out a failover window
        (transports/server.py standby_of)."""
        from dynamo_tpu.runtime.transports.tcp import ControlPlaneClient
        if addrs is None and "," in host:
            addrs = []
            for part in host.split(","):
                h, _, p = part.strip().rpartition(":")
                addrs.append((h or "127.0.0.1", int(p) if p else port))
        client = await ControlPlaneClient(host, port, addrs=addrs).connect()
        rt = cls(client, client, worker_id, advertise_host)
        rt._client = client
        await rt._init_lease()
        return rt

    async def _init_lease(self):
        self.lease = await self.kv.grant_lease(LEASE_TTL_S)

        async def watch():
            await self.lease.lost.wait()
            log.warning("primary lease lost; shutting down runtime %s",
                        self.worker_id)
            await self.shutdown()

        self._lease_watch = asyncio.create_task(watch())
        # Heartbeat for planes whose Lease exposes a direct keep_alive hook
        # (memory plane); the TCP client runs its own keepalive loop.
        keep_alive = getattr(self.lease, "keep_alive", None)
        if callable(keep_alive):
            async def heartbeat():
                while not self.shutdown_event.is_set():
                    await asyncio.sleep(LEASE_TTL_S / 3)
                    keep_alive()

            self._lease_heartbeat = asyncio.create_task(heartbeat())

    # -- accessors -----------------------------------------------------------

    def namespace(self, name: str) -> Namespace:
        if name not in self._namespaces:
            self._namespaces[name] = Namespace(self, name)
        return self._namespaces[name]

    async def data_plane(self) -> DataPlaneServer:
        # lock: a concurrent caller must not see the server pre-start
        # (its advertised port would still be 0)
        async with self._data_plane_lock:
            if self._data_plane is None:
                server = DataPlaneServer(advertise_host=self._advertise_host)
                await server.start()
                self._data_plane = server
        return self._data_plane

    def register_served(self, served) -> None:
        self._served.append(served)

    # -- lifecycle -----------------------------------------------------------

    async def shutdown(self):
        # re-entrancy is guarded by its own flag; the EVENT is set LAST —
        # a caller awaiting shutdown_event (run.py worker mode) may exit
        # the process the moment it fires, which would cancel this very
        # coroutine mid-cleanup if the event were set up front
        # (code-review r5: graceful drain lost its lease revoke)
        if getattr(self, "_shutting_down", False):
            return
        self._shutting_down = True
        for served in self._served:
            try:
                await served.shutdown()
            except Exception:  # dynalint: swallow-ok=shutdown-sweep-continues
                pass
        if self._lease_watch:
            self._lease_watch.cancel()
        hb = getattr(self, "_lease_heartbeat", None)
        if hb:
            hb.cancel()
        if self.lease is not None:
            try:
                await self.lease.revoke()
            except Exception:  # dynalint: swallow-ok=lease-expiry-covers-failed-revoke
                pass
        if self._data_plane is not None:
            await self._data_plane.stop()
        client = getattr(self, "_client", None)
        if client is not None:
            await client.close()
        self.shutdown_event.set()
