"""Gray-failure detection: fleet-relative health scoring for fail-slow
workers (docs/RESILIENCE.md "Fail-slow failure model").

Every other failure plane in this repo ships crash-stop semantics —
breakers trip on *errors* (frontend/reliability.py), leases expire on
*death* (runtime/discovery.py), watch deletes fence *corpses*
(kv_router/router.py), transfer frontiers resume after *cuts* — but a
worker with a throttled chip, a flaky NIC, or an NVMe hiccup stays
alive, answers heartbeats, and silently drags fleet p99 with zero
counters moving. This module closes that gap: it folds per-instance
latency evidence the serving path already produces (per-attempt wall
times from ReliableClient, TTFT/ITL rollup series, TransferCostModel
per-link signed estimator-error EWMAs) into one per-worker health score
and emits SLOW-enter/SLOW-exit decisions with hysteresis.

Design invariants, each load-bearing:

- **Fleet-relative, robust.** A worker is slow relative to the *fleet
  median*, scored with a MAD z-score (z = 0.6745·(x − med)/MAD). The
  median/MAD pair is breakdown-resistant: one gray-failed worker (or a
  small clique) cannot drag the baseline toward itself the way a mean/
  stddev pair would, so the sick stand out instead of normalizing
  themselves. MAD is floored at a fraction of the median so a very
  tight fleet doesn't hair-trigger on microsecond noise.
- **Min-evidence floor.** A worker with fewer than ``min_evidence``
  observations scores 1.0 and can never be condemned — cold workers
  (fresh restart, first requests still compiling) are exempt, which is
  what makes "zero false ejections of healthy workers" provable in the
  chaos A/B.
- **Hysteresis.** Entering SLOW takes ``enter_evals`` *consecutive*
  evaluations over ``z_enter``; leaving takes ``exit_evals`` consecutive
  evaluations under ``z_exit`` (< z_enter). One outlier sample flips
  nothing in either direction.
- **Deterministic and replayable.** Scoring is a pure function of the
  observation stream and the injected clock; every SLOW transition is
  appended to ``timeline`` so two same-seed runs (SimCluster
  ``fail_slow_ab``) produce bit-identical decision timelines.

The score feeds three consumers: the router logit
(kv_router/scheduler.py sheds load from degraded workers *before* they
trip), the breaker's latency-tripped SLOW state
(frontend/reliability.py — reduced dispatch share, probe-based
recovery, never full eviction), and the hedging trigger (a request on a
SLOW primary hedges sooner). /metrics surfaces the fold of HEALTH_STATS
and HEDGE_STATS below as ``llm_health_*`` / ``llm_hedge_*``.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional


class HealthStats:
    """Process-local detection counters (/metrics: llm_health_*), the
    same render-time-fold pattern as kv_router/stats.py ROUTER_STATS."""

    FIELDS = (
        "evals",            # scoring evaluations run
        "slow_enters",      # SLOW-enter decisions (hysteresis satisfied)
        "slow_exits",       # SLOW-exit decisions (recovered)
        "workers_tracked",  # workers with any latency evidence
        "workers_slow",     # workers currently marked SLOW
        "cold_exempt",      # workers under the min-evidence floor
        "min_score_milli",  # worst current health score x1000
    )

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        for name in self.FIELDS:
            setattr(self, name, 0)

    def snapshot(self) -> dict:
        return {name: getattr(self, name) for name in self.FIELDS}


class HedgeStats:
    """Hedged-dispatch counters (/metrics: llm_hedge_*)."""

    FIELDS = (
        "fired",               # hedge attempts dispatched
        "wins",                # hedge produced the first token
        "losses",              # primary produced the first token
        "budget_denied",       # hedge wanted but per-class budget said no
        "suppressed_commit",   # hedge suppressed: tokens already committed
        "no_candidate",        # hedge wanted but no healthy second instance
    )

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        for name in self.FIELDS:
            setattr(self, name, 0)
        self.fired_by_class: Dict[str, int] = {}

    def snapshot(self) -> dict:
        out = {name: getattr(self, name) for name in self.FIELDS}
        out["fired_by_class"] = dict(self.fired_by_class)
        return out


HEALTH_STATS = HealthStats()
HEDGE_STATS = HedgeStats()


class HedgeBudget:
    """Per-class hedge budget: hedges may consume at most
    ``budget_frac`` of the class's request volume (plus a small burst
    allowance so the first sick request of a quiet class can still
    hedge). A sick fleet cannot melt itself with duplicate work: when
    every primary is slow, hedging saturates at the budget instead of
    doubling total dispatch."""

    def __init__(self, budget_frac: float = 0.1, burst: int = 2):
        self.budget_frac = float(budget_frac)
        self.burst = int(burst)
        self._requests: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}

    def on_request(self, cls: str = "") -> None:
        self._requests[cls] = self._requests.get(cls, 0) + 1

    def try_fire(self, cls: str = "") -> bool:
        """True (and charge the budget) if a hedge may fire now."""
        allowed = self.budget_frac * self._requests.get(cls, 0) + self.burst
        if self._fired.get(cls, 0) + 1 > allowed:
            return False
        self._fired[cls] = self._fired.get(cls, 0) + 1
        return True

    def snapshot(self) -> dict:
        return {"requests": dict(self._requests),
                "fired": dict(self._fired)}


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    if n % 2:
        return s[mid]
    return 0.5 * (s[mid - 1] + s[mid])


class HealthScorer:
    """Per-worker health from fleet-relative robust latency statistics.

    ``observe(worker, seconds)`` feeds per-attempt service time (any
    consistent latency signal works: attempt wall time in the
    reliability layer, TTFT in the sim). ``observe_link_err(worker,
    frac)`` folds the transfer plane's signed estimator-error EWMA as
    secondary evidence: a link persistently *slower than its own
    estimate* (positive error) inflates the worker's effective z.
    ``evaluate(now)`` recomputes scores and returns the SLOW
    transitions that fired this round.
    """

    def __init__(self,
                 z_enter: float = 3.0,
                 z_exit: float = 1.5,
                 enter_evals: int = 2,
                 exit_evals: int = 2,
                 min_evidence: int = 8,
                 alpha: float = 0.3,
                 err_weight: float = 2.0,
                 z_max: float = 8.0,
                 mad_floor_frac: float = 0.05,
                 clock: Optional[Callable[[], float]] = None):
        if z_exit >= z_enter:
            raise ValueError("hysteresis requires z_exit < z_enter")
        self.z_enter = float(z_enter)
        self.z_exit = float(z_exit)
        self.enter_evals = int(enter_evals)
        self.exit_evals = int(exit_evals)
        self.min_evidence = int(min_evidence)
        self.alpha = float(alpha)
        self.err_weight = float(err_weight)
        self.z_max = float(z_max)
        self.mad_floor_frac = float(mad_floor_frac)
        self._clock = clock or time.monotonic
        # per-worker evidence
        self._ewma: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._link_err: Dict[str, float] = {}
        # per-worker decision state
        self._score: Dict[str, float] = {}
        self._z: Dict[str, float] = {}
        self._slow: Dict[str, bool] = {}
        self._enter_streak: Dict[str, int] = {}
        self._exit_streak: Dict[str, int] = {}
        # replayable decision record: {"t", "worker", "event", "z", "score"}
        self.timeline: List[dict] = []

    # -- evidence -------------------------------------------------------------

    def observe(self, worker: str, seconds: float) -> None:
        """One latency sample for ``worker`` (attempt wall time, TTFT)."""
        v = float(seconds)
        prev = self._ewma.get(worker)
        self._ewma[worker] = v if prev is None else (
            self.alpha * v + (1.0 - self.alpha) * prev)
        self._count[worker] = self._count.get(worker, 0) + 1

    def observe_link_err(self, worker: str, err_frac: float) -> None:
        """Signed transfer estimator error for a link terminating at
        ``worker`` (TransferCostModel.est_err_frac): positive = the link
        is slower than its own history predicts — gray-NIC evidence."""
        prev = self._link_err.get(worker)
        v = float(err_frac)
        self._link_err[worker] = v if prev is None else (
            self.alpha * v + (1.0 - self.alpha) * prev)

    def forget(self, worker: str) -> None:
        """Evict all state for a dead instance (watch-delete hook): a
        reused worker name must start cold, not inherit a corpse's z."""
        for d in (self._ewma, self._count, self._link_err, self._score,
                  self._z, self._slow, self._enter_streak,
                  self._exit_streak):
            d.pop(worker, None)

    def reset(self) -> None:
        self.__init__(z_enter=self.z_enter, z_exit=self.z_exit,
                      enter_evals=self.enter_evals,
                      exit_evals=self.exit_evals,
                      min_evidence=self.min_evidence, alpha=self.alpha,
                      err_weight=self.err_weight, z_max=self.z_max,
                      mad_floor_frac=self.mad_floor_frac,
                      clock=self._clock)

    # -- scoring --------------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Recompute fleet-relative scores; returns the SLOW transitions
        (timeline events) that fired this evaluation."""
        t = self._clock() if now is None else float(now)
        HEALTH_STATS.evals += 1
        warm = {w: x for w, x in self._ewma.items()
                if self._count.get(w, 0) >= self.min_evidence}
        cold = len(self._ewma) - len(warm)
        events: List[dict] = []
        if len(warm) >= 3:
            med = _median(list(warm.values()))
            mad = _median([abs(x - med) for x in warm.values()])
            mad = max(mad, self.mad_floor_frac * max(med, 1e-9), 1e-9)
            for w, x in warm.items():
                z = 0.6745 * (x - med) / mad
                z += self.err_weight * max(0.0, self._link_err.get(w, 0.0))
                self._z[w] = z
                self._score[w] = min(1.0, max(
                    0.0, 1.0 - max(0.0, z) / self.z_max))
                events.extend(self._hysteresis(w, z, t))
        # cold workers (and everyone, pre-quorum) are healthy by fiat
        for w in self._ewma:
            if w not in warm:
                self._z[w] = 0.0
                self._score[w] = 1.0
        HEALTH_STATS.workers_tracked = len(self._ewma)
        HEALTH_STATS.workers_slow = sum(
            1 for v in self._slow.values() if v)
        HEALTH_STATS.cold_exempt = cold
        scores = [v for v in self._score.values()]
        HEALTH_STATS.min_score_milli = int(
            1000 * (min(scores) if scores else 1.0))
        return events

    def _hysteresis(self, worker: str, z: float, t: float) -> List[dict]:
        events: List[dict] = []
        if not self._slow.get(worker, False):
            if z >= self.z_enter:
                streak = self._enter_streak.get(worker, 0) + 1
                self._enter_streak[worker] = streak
                if streak >= self.enter_evals:
                    self._slow[worker] = True
                    self._enter_streak[worker] = 0
                    HEALTH_STATS.slow_enters += 1
                    events.append(self._record(
                        t, worker, "slow_enter", z))
            else:
                self._enter_streak[worker] = 0
        else:
            if z <= self.z_exit:
                streak = self._exit_streak.get(worker, 0) + 1
                self._exit_streak[worker] = streak
                if streak >= self.exit_evals:
                    self._slow[worker] = False
                    self._exit_streak[worker] = 0
                    HEALTH_STATS.slow_exits += 1
                    events.append(self._record(
                        t, worker, "slow_exit", z))
            else:
                self._exit_streak[worker] = 0
        return events

    def _record(self, t: float, worker: str, event: str, z: float) -> dict:
        ev = {"t": round(float(t), 6), "worker": worker, "event": event,
              "z": round(float(z), 4),
              "score": round(self._score.get(worker, 1.0), 4)}
        self.timeline.append(ev)
        return ev

    # -- consumers ------------------------------------------------------------

    def score(self, worker: str) -> float:
        """Health in [0, 1]; 1.0 absent evidence (never condemn cold)."""
        return self._score.get(worker, 1.0)

    def zscore(self, worker: str) -> float:
        return self._z.get(worker, 0.0)

    def is_slow(self, worker: str) -> bool:
        return self._slow.get(worker, False)

    def slow_workers(self) -> List[str]:
        return sorted(w for w, v in self._slow.items() if v)

    def evidence(self, worker: str) -> int:
        return self._count.get(worker, 0)

    def snapshot(self) -> dict:
        return {
            "workers": {
                w: {"score": round(self._score.get(w, 1.0), 4),
                    "z": round(self._z.get(w, 0.0), 4),
                    "n": self._count.get(w, 0),
                    "slow": self._slow.get(w, False)}
                for w in sorted(self._ewma)},
            "slow": self.slow_workers(),
            "timeline_len": len(self.timeline),
        }


# process-wide scorer the reliability layer and /metrics folds consult
HEALTH = HealthScorer()
