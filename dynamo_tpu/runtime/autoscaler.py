"""Closed-loop fleet autoscaler: SLO-driven prefill<->decode re-roling.

ROADMAP item 4, the controller that closes the loop between the PR-10
sensor plane and the actuators this repo already ships:

- **sensors**: the fleet rollup's per-role aggregates
  (observability/fleet.py `role/{role}/*` series: queue depth,
  occupancy, availability) and the SLO watchdog's TTFT/ITL burn rates
  (observability/slo.py) — `signals_from_store`/`signals_from_rollup`
  fold them into one `FleetSignals` snapshot;
- **actuators**: graceful drain + role re-registration
  (`ServedEndpoint.re_role` on real workers, `SimWorker.set_role` in
  the simcluster), plus shed/add-N of whole workers.

The reference Dynamo ships this as the planner ("this decode worker
becomes a prefill worker"); what makes OUR controller shippable is the
robustness machinery around the decision function, because a naive
controller is a better outage generator than any traffic storm:

- **cooldown**: after any actuation, no further decisions for
  `cooldown_s` — the fleet must be allowed to settle before the
  controller reads its own wake;
- **hysteresis**: a pressure direction must hold for
  `hysteresis_ticks` consecutive ticks before it actuates — a 1-tick
  blip (one slow scrape, one burst) never moves a worker;
- **do-no-harm guards**: a re-role/shed is REFUSED when it would take
  the source role below its configured minimum, or while a previous
  drain is still migrating streams (`drains_active > 0`) — two
  concurrent drains can strand streams with no migration target;
- **degraded freeze**: while the router rides its stale-snapshot
  degraded mode (runtime/cpstats.py CP_STATS.router_degraded — the
  sanctioned state PR 7 manages, same exemption the SLO watchdog's
  `degraded_exempt` specs take) the controller makes NO decisions and
  counts `frozen_degraded`: acting on a stale snapshot re-roles
  workers against traffic that is not what the sensors claim;
- **bounded actuation**: at most `max_moves` workers per decision and
  `max_moves_per_window` per `window_s` — a wedged sensor pinned at
  "bad" can never mass-drain the fleet, it saturates the bound and
  pages a human instead.

Decisions are a pure function of the `FleetSignals` sequence (plus the
candidate worker lists), so a seeded virtual-clock storm replays the
exact decision timeline bit-identically — the AUTOSCALE_r12.json
contract (tools/fleet_storm.py, tests/test_autoscaler.py).

The module also carries the LOCAL self-tuning leg of ROADMAP item 4:
`MixedBudgetTuner` watches the per-step ledger's padding-waste
(observability/ledger.py `useful_total`/`padded_total`) and adapts the
engine scheduler's `mixed_token_budget` — a fleet rebalance changes
the traffic shape each engine sees, and the bucket ladder that fit the
old shape burns tokens on padding under the new one. Same cooldown +
hysteresis + bounded-step discipline, applied through
`Scheduler.set_mixed_token_budget` (docs/PERF.md §3b knob guidance).

docs/RESILIENCE.md "Fleet rebalancing" documents the decision rules
and the storm runbook; `llm_autoscaler_*` gauges render on both
/metrics surfaces (docs/OBSERVABILITY.md §9).
"""
from __future__ import annotations

import dataclasses
import logging
import time
from collections import deque
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

log = logging.getLogger("dynamo_tpu.autoscaler")

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"


class AutoscalerStats:
    """Process-local controller counters (/metrics: llm_autoscaler_*).

    Same pattern as kv_router/stats.py ROUTER_STATS: plain numbers
    bumped on the decision path, folded into Prometheus gauges at
    /metrics render time by frontend/service.py and
    observability/exporter.py. `last_decision_age_s` is derived at
    snapshot time from the last actuation's timestamp — the "is the
    controller alive or wedged" signal an operator reads first."""

    FIELDS = (
        "decisions_total",            # actuated decisions, all kinds
        "decisions_re_role_to_prefill",
        "decisions_re_role_to_decode",
        "decisions_add",
        "decisions_shed",
        "cooldown_suppressed",        # pressure seen inside cooldown
        "hysteresis_suppressed",      # pressure not yet sustained
        "guard_blocked",              # do-no-harm refusals
        "frozen_degraded",            # ticks frozen by degraded mode
        "last_decision_age_s",        # seconds since the last actuation
        "budget_adjustments",         # MixedBudgetTuner actuations
        "budget_current",             # last applied mixed_token_budget
    )

    def __init__(self, clock: Callable[[], float] = time.time):
        self._clock = clock
        self.reset()

    def reset(self) -> None:
        for name in self.FIELDS:
            setattr(self, name, 0)
        self.last_decision_ts: Optional[float] = None

    def note_decision(self, kind: str, ts: float) -> None:
        self.decisions_total += 1
        field = "decisions_" + kind
        setattr(self, field, getattr(self, field) + 1)
        self.last_decision_ts = ts

    def snapshot(self) -> Dict[str, float]:
        out = {name: getattr(self, name) for name in self.FIELDS}
        if self.last_decision_ts is not None:
            out["last_decision_age_s"] = max(
                0.0, self._clock() - self.last_decision_ts)
        return out


AUTOSCALER_STATS = AutoscalerStats()


@dataclasses.dataclass
class RoleState:
    """One role's aggregate view (the rollup's `role/{role}/*` series)."""

    workers: int = 0            # ready (non-draining) workers
    draining: int = 0
    queue_depth: float = 0.0    # waiting requests across the role
    occupancy: float = 0.0      # active slots / total slots
    availability: float = 1.0   # ready / (ready + draining)


@dataclasses.dataclass
class FleetSignals:
    """One controller tick's sensor snapshot. A pure value: the
    decision function sees nothing else, which is what makes a seeded
    storm's decision timeline replayable."""

    ts: float
    roles: Dict[str, RoleState]
    ttft_burn: float = 0.0       # short-window burn rate of the TTFT SLO
    itl_burn: float = 0.0        # short-window burn rate of the ITL SLO
    ttft_firing: bool = False
    itl_firing: bool = False
    degraded: bool = False       # router stale-snapshot degraded mode
    drains_active: int = 0       # re-role/drain actuations still migrating


def signals_from_store(store, watchdog, ts: float,
                       ttft_slo: str = "ttft_p95",
                       itl_slo: str = "itl_p99",
                       degraded: bool = False,
                       drains_active: int = 0) -> FleetSignals:
    """Build FleetSignals from the rollup's SeriesStore schema
    (`role/{role}/{field}`) plus the watchdog's burn state. Shared by
    the live path (signals_from_rollup) and the virtual-clock storm,
    so the controller consumes ONE sensor schema everywhere."""
    roles: Dict[str, RoleState] = {}
    for name in store.names("role/"):
        _, role, field = name.split("/", 2)
        series = store.get(name)
        latest = series.latest() if series is not None else None
        if latest is None:
            continue
        st = roles.setdefault(role, RoleState())
        if field == "workers":
            st.workers = int(latest)
        elif field == "draining":
            st.draining = int(latest)
        elif field == "queue_depth":
            st.queue_depth = latest
        elif field == "occupancy":
            st.occupancy = latest
        elif field == "availability":
            st.availability = latest
    sig = FleetSignals(ts=ts, roles=roles, degraded=degraded,
                       drains_active=drains_active)
    if watchdog is not None:
        for spec_name, st in watchdog.states.items():
            if ttft_slo in spec_name:
                sig.ttft_burn = st.burn_short or 0.0
                sig.ttft_firing = st.firing
            elif itl_slo in spec_name:
                sig.itl_burn = st.burn_short or 0.0
                sig.itl_firing = st.firing
    return sig


def signals_from_rollup(rollup, watchdog, ts: Optional[float] = None,
                        ttft_slo: str = "ttft_p95",
                        itl_slo: str = "itl_p99",
                        drains_active: int = 0) -> FleetSignals:
    """The live-fleet sensor fold: rollup series (recorded by
    `FleetRollup.scrape_once`, incl. the per-role aggregates) +
    watchdog burn state + the router's degraded flag."""
    from dynamo_tpu.runtime.cpstats import CP_STATS
    if ts is None:
        ts = rollup.clock()
    return signals_from_store(rollup.store, watchdog, ts,
                              ttft_slo=ttft_slo, itl_slo=itl_slo,
                              degraded=bool(CP_STATS.router_degraded),
                              drains_active=drains_active)


@dataclasses.dataclass(frozen=True)
class AutoscalerConfig:
    """Controller policy. The defaults are tuned for ~1 Hz ticks over
    the rollup's 1 s series (docs/RESILIENCE.md "Fleet rebalancing"
    has the knob guidance)."""

    min_prefill: int = 1          # do-no-harm floor per role
    min_decode: int = 1
    cooldown_s: float = 20.0      # quiet period after ANY actuation
    hysteresis_ticks: int = 3     # sustained-pressure floor
    max_moves: int = 2            # workers per decision
    max_moves_per_window: int = 8   # bounded actuation over window_s
    window_s: float = 120.0
    queue_hi: float = 3.0         # waiting per prefill worker => hot
    queue_lo: float = 0.25        # => cold (shed candidate)
    occ_hi: float = 0.85          # decode slot occupancy => hot
    occ_lo: float = 0.30          # => cold
    burn_hi: float = 1.0          # SLO burn rate counted as pressure
    # steady-state homing: with both roles quiet, drift the split back
    # toward this prefill fraction (None = no homing — the fleet stays
    # wherever the last storm left it). The reference planner's
    # configured baseline ratio; what re-roles flash-crowd conscripts
    # back to decode once the queue drains.
    target_prefill_frac: Optional[float] = None

    def role_min(self, role: str) -> int:
        return self.min_prefill if role == ROLE_PREFILL else self.min_decode


@dataclasses.dataclass(frozen=True)
class Decision:
    """One actuated controller decision (the timeline unit)."""

    ts: float
    kind: str                     # re_role_to_prefill | re_role_to_decode
    #                               | add | shed
    workers: Tuple[str, ...]      # targets ('' for add: count only)
    from_role: str = ""
    to_role: str = ""
    count: int = 0
    reason: str = ""

    def to_dict(self) -> dict:
        return {"ts": round(self.ts, 3), "kind": self.kind,
                "workers": list(self.workers),
                "from_role": self.from_role, "to_role": self.to_role,
                "count": self.count, "reason": self.reason}


class Cooldown:
    """Per-controller actuation cooldown (virtual-clock friendly)."""

    def __init__(self, cooldown_s: float):
        self.cooldown_s = cooldown_s
        self.last_ts: Optional[float] = None

    def ready(self, ts: float) -> bool:
        return self.last_ts is None or ts - self.last_ts >= self.cooldown_s

    def note(self, ts: float) -> None:
        self.last_ts = ts


class Hysteresis:
    """Consecutive-tick streak per pressure direction; a direction
    change resets the streak, so flapping pressure never actuates."""

    def __init__(self):
        self.direction: Optional[str] = None
        self.streak = 0

    def observe(self, direction: Optional[str]) -> int:
        if direction is None:
            self.direction, self.streak = None, 0
        elif direction == self.direction:
            self.streak += 1
        else:
            self.direction, self.streak = direction, 1
        return self.streak


class FleetAutoscaler:
    """The decision loop. `decide(signals, candidates)` is pure;
    `actuate()` hands decisions to the injected async actuator (the
    storm's `SimWorker.set_role` driver, a real fleet's
    `ServedEndpoint.re_role`). This class OWNS the cooldown and
    hysteresis objects the dynalint R17 actuation contract keys on."""

    def __init__(self, cfg: Optional[AutoscalerConfig] = None,
                 actuator: Optional[
                     Callable[[Decision], Awaitable[None]]] = None,
                 stats: Optional[AutoscalerStats] = None):
        self.cfg = cfg or AutoscalerConfig()
        self.actuator = actuator
        self.stats = stats if stats is not None else AUTOSCALER_STATS
        self.cooldown = Cooldown(self.cfg.cooldown_s)
        self.hysteresis = Hysteresis()
        self._window: deque = deque()     # (ts, moves) actuation history
        self.timeline: List[dict] = []    # actuated decisions, in order
        self.frozen_ticks = 0
        self.ticks = 0

    # -- pressure classification ---------------------------------------------

    def _plan(self, sig: FleetSignals) -> Optional[Tuple[str, str]]:
        """(direction, reason) for this tick, or None when balanced."""
        cfg = self.cfg
        p = sig.roles.get(ROLE_PREFILL, RoleState())
        d = sig.roles.get(ROLE_DECODE, RoleState())
        queue_per_p = p.queue_depth / max(1, p.workers)
        prefill_hot = (sig.ttft_firing or sig.ttft_burn >= cfg.burn_hi
                       or queue_per_p >= cfg.queue_hi)
        decode_hot = (sig.itl_firing or sig.itl_burn >= cfg.burn_hi
                      or d.occupancy >= cfg.occ_hi)
        if prefill_hot and decode_hot:
            return ("add", f"both roles hot (queue/prefill={queue_per_p:.2f},"
                           f" decode occ={d.occupancy:.2f})")
        if prefill_hot:
            return ("re_role_to_prefill",
                    f"ttft burn={sig.ttft_burn:.2f} firing={sig.ttft_firing}"
                    f" queue/prefill={queue_per_p:.2f}")
        if decode_hot:
            return ("re_role_to_decode",
                    f"itl burn={sig.itl_burn:.2f} firing={sig.itl_firing}"
                    f" decode occ={d.occupancy:.2f}")
        prefill_quiet = queue_per_p <= cfg.queue_lo and not sig.ttft_firing
        decode_cold = d.occupancy <= cfg.occ_lo and not sig.itl_firing
        # shed demands REAL idleness on both sides (occupancy floors,
        # not just an empty queue — an empty queue with busy workers
        # means capacity exactly matches demand, not excess)
        prefill_cold = prefill_quiet and p.occupancy <= cfg.occ_lo
        if cfg.target_prefill_frac is not None:
            # homing: both roles quiet and the split off the configured
            # steady-state ratio — drift back, one paced decision at a
            # time (what returns flash-crowd conscripts to decode)
            total = p.workers + d.workers
            target_p = int(round(cfg.target_prefill_frac * total))
            if p.workers > target_p and prefill_quiet \
                    and p.occupancy <= 2 * cfg.occ_lo:
                return ("re_role_to_decode",
                        f"homing: prefill {p.workers} > target {target_p} "
                        f"while idle (occ={p.occupancy:.2f})")
            if p.workers < target_p and decode_cold:
                return ("re_role_to_prefill",
                        f"homing: prefill {p.workers} < target {target_p} "
                        f"while decode idle (occ={d.occupancy:.2f})")
        if prefill_cold and decode_cold:
            return ("shed", f"fleet idle (queue/prefill={queue_per_p:.2f},"
                            f" prefill occ={p.occupancy:.2f},"
                            f" decode occ={d.occupancy:.2f})")
        return None

    # -- bounded actuation budget --------------------------------------------

    def _window_budget(self, ts: float) -> int:
        while self._window and ts - self._window[0][0] > self.cfg.window_s:
            self._window.popleft()
        used = sum(n for _, n in self._window)
        return max(0, self.cfg.max_moves_per_window - used)

    # -- the decision function -----------------------------------------------

    def decide(self, sig: FleetSignals,
               candidates: Dict[str, List[str]]) -> List[Decision]:
        """One controller tick. `candidates` maps role -> orderable
        worker ids (preference order: the caller puts the least-loaded
        first). Returns the actuated decisions (0 or 1 per tick);
        every suppression lands on a stats counter instead."""
        cfg, stats = self.cfg, self.stats
        self.ticks += 1
        if sig.degraded:
            # degraded freeze: the snapshot is sanctioned-stale; hold
            # everything (incl. the hysteresis streak) until it clears
            self.frozen_ticks += 1
            stats.frozen_degraded += 1
            return []
        planned = self._plan(sig)
        streak = self.hysteresis.observe(planned[0] if planned else None)
        if planned is None:
            return []
        direction, reason = planned
        if streak < cfg.hysteresis_ticks:
            stats.hysteresis_suppressed += 1
            return []
        if not self.cooldown.ready(sig.ts):
            stats.cooldown_suppressed += 1
            return []
        if sig.drains_active > 0:
            # do-no-harm: a previous drain is still migrating streams
            stats.guard_blocked += 1
            return []
        budget = min(cfg.max_moves, self._window_budget(sig.ts))
        if budget <= 0:
            stats.guard_blocked += 1
            return []
        decision = self._build(sig, candidates, direction, reason, budget)
        if decision is None:
            stats.guard_blocked += 1
            return []
        self.cooldown.note(sig.ts)
        self._window.append((sig.ts, max(1, decision.count)))
        stats.note_decision(decision.kind, sig.ts)
        self.timeline.append(decision.to_dict())
        return [decision]

    def _build(self, sig: FleetSignals, candidates: Dict[str, List[str]],
               direction: str, reason: str,
               budget: int) -> Optional[Decision]:
        cfg = self.cfg
        roles = sig.roles

        def headroom(role: str) -> int:
            st = roles.get(role, RoleState())
            return st.workers - st.draining - cfg.role_min(role)

        if direction in ("re_role_to_prefill", "re_role_to_decode"):
            src = ROLE_DECODE if direction.endswith("prefill") else \
                ROLE_PREFILL
            dst = ROLE_PREFILL if src == ROLE_DECODE else ROLE_DECODE
            n = min(budget, headroom(src), len(candidates.get(src, ())))
            if n <= 0:
                return None     # role-minimum guard (or no candidates)
            return Decision(sig.ts, direction,
                            tuple(candidates[src][:n]),
                            from_role=src, to_role=dst, count=n,
                            reason=reason)
        if direction == "add":
            # target the hotter role; actuation brings spare/new workers
            p = roles.get(ROLE_PREFILL, RoleState())
            d = roles.get(ROLE_DECODE, RoleState())
            queue_per_p = p.queue_depth / max(1, p.workers)
            dst = ROLE_PREFILL if (queue_per_p / max(cfg.queue_hi, 1e-9)
                                   >= d.occupancy / max(cfg.occ_hi, 1e-9)) \
                else ROLE_DECODE
            return Decision(sig.ts, "add", (), to_role=dst, count=budget,
                            reason=reason)
        if direction == "shed":
            # shed from the colder (lower-utilization) role, floor-guarded
            p = roles.get(ROLE_PREFILL, RoleState())
            d = roles.get(ROLE_DECODE, RoleState())
            queue_per_p = p.queue_depth / max(1, p.workers)
            src = ROLE_PREFILL if (queue_per_p / max(cfg.queue_hi, 1e-9)
                                   <= d.occupancy / max(cfg.occ_hi, 1e-9)) \
                else ROLE_DECODE
            n = min(1, budget, headroom(src), len(candidates.get(src, ())))
            if n <= 0:
                return None
            return Decision(sig.ts, "shed", tuple(candidates[src][:n]),
                            from_role=src, count=n, reason=reason)
        return None

    async def actuate(self, decisions: List[Decision]) -> None:
        """Hand actuated decisions to the injected actuator, one at a
        time and in order — the cooldown owned by this controller is
        what keeps consecutive drains apart."""
        if self.actuator is None:
            return
        for d in decisions:
            await self.actuator(d)

    def summary(self) -> dict:
        return {
            "ticks": self.ticks,
            "decisions": len(self.timeline),
            "frozen_ticks": self.frozen_ticks,
            "timeline": list(self.timeline),
        }


class MixedBudgetTuner:
    """Ledger-driven `mixed_token_budget` self-tuning (item-4 local leg).

    Watches the windowed padding-waste fraction of the per-step ledger
    (delta of `useful_total`/`padded_total` between ticks — NOT the
    cumulative fraction, which a long healthy history would pin) and
    adapts the scheduler's mixed-step token budget through
    `Scheduler.set_mixed_token_budget`:

    - waste above `pad_hi`: the [Bb, Tb] buckets are too wide for the
      live traffic — shrink the budget by `step_frac` (bounded below
      by `min_budget`, never to 0: 0 flips the engine to legacy
      alternating, a MODE change no tuner should make silently);
    - waste below `pad_lo` with work waiting: the ladder has headroom —
      grow by `step_frac` (bounded by `max_budget`) so prefill chunks
      ride along with more decode rows per step.

    Same safety discipline as the fleet controller: per-adjustment
    cooldown, consecutive-tick hysteresis, a minimum evidence window
    (`min_tokens` padded tokens between decisions), and bounded step
    size — a few bad steps can never collapse the budget."""

    def __init__(self, scheduler, ledger,
                 pad_lo: float = 0.10, pad_hi: float = 0.30,
                 step_frac: float = 0.25,
                 min_budget: int = 128, max_budget: int = 4096,
                 cooldown_s: float = 15.0, hysteresis_ticks: int = 2,
                 min_tokens: int = 512,
                 stats: Optional[AutoscalerStats] = None):
        self.scheduler = scheduler
        self.ledger = ledger
        self.pad_lo, self.pad_hi = pad_lo, pad_hi
        self.step_frac = step_frac
        self.min_budget, self.max_budget = min_budget, max_budget
        self.min_tokens = min_tokens
        self.cooldown = Cooldown(cooldown_s)
        self.hysteresis = Hysteresis()
        self.hysteresis_ticks = hysteresis_ticks
        self.stats = stats if stats is not None else AUTOSCALER_STATS
        self._useful0 = ledger.useful_total
        self._padded0 = ledger.padded_total
        self.adjustments: List[dict] = []

    def window_pad_frac(self) -> Optional[float]:
        """Padding-waste fraction since the last consumed window; None
        below the evidence floor."""
        dp = self.ledger.padded_total - self._padded0
        if dp < self.min_tokens:
            return None
        du = self.ledger.useful_total - self._useful0
        return max(0.0, 1.0 - du / dp)

    def tick(self, ts: float) -> Optional[int]:
        """One evaluation; returns the newly applied budget when an
        adjustment actuated, else None."""
        pad = self.window_pad_frac()
        if pad is None:
            return None
        # window consumed: the next verdict needs fresh evidence
        self._useful0 = self.ledger.useful_total
        self._padded0 = self.ledger.padded_total
        current = self.scheduler.mixed_token_budget
        if current <= 0:
            return None      # legacy alternating mode: not ours to flip
        direction = ("shrink" if pad > self.pad_hi
                     else "grow" if pad < self.pad_lo else None)
        streak = self.hysteresis.observe(direction)
        if direction is None or streak < self.hysteresis_ticks:
            return None
        if not self.cooldown.ready(ts):
            return None
        if direction == "shrink":
            target = max(self.min_budget,
                         int(current * (1.0 - self.step_frac)))
        else:
            target = min(self.max_budget,
                         int(current * (1.0 + self.step_frac)))
        if target == current:
            return None
        applied = self.scheduler.set_mixed_token_budget(target)
        self.cooldown.note(ts)
        self.stats.budget_adjustments += 1
        self.stats.budget_current = applied
        self.adjustments.append({
            "ts": round(ts, 3), "pad_frac": round(pad, 4),
            "direction": direction, "from": current, "to": applied})
        log.info("mixed_token_budget %s: %d -> %d (pad_frac=%.3f)",
                 direction, current, applied, pad)
        return applied
