"""Process-local control-plane health counters (/metrics: llm_cp_*).

Same pattern as runtime.component.DRAIN_STATS and runtime.integrity.STATS:
plain ints bumped on the hot paths, folded into Prometheus gauges at
/metrics render time by frontend/service.py and observability/exporter.py.
The sources:

- the Client watch pump (runtime/component.py): queue depth, events
  applied, events coalesced away by per-tick batching, resyncs after a
  watch-stream disconnect;
- the KV indexer (kv_router/indexer.py): live radix node count and the
  incremental-eviction backlog;
- the KvRouter event pump (kv_router/router.py): event-plane lag
  (publish ts → apply time), event backlog, and the stale-snapshot
  degraded-mode flag + transition count.

Values are process-local and last-writer-wins across multiple watchers /
indexers in one process — they answer "is THIS process's control plane
healthy", which is the per-instance question /metrics exists for.
"""
from __future__ import annotations


class ControlPlaneStats:
    FIELDS = (
        "watch_queue_depth",        # latest observed watch backlog
        "watch_events_applied",     # cumulative events applied
        "watch_events_coalesced",   # cumulative events folded by batching
        "watch_resyncs",            # watch-stream deaths -> snapshot resyncs
        "indexer_nodes",            # live radix-tree nodes
        "indexer_eviction_backlog", # nodes queued for incremental eviction
        "event_lag_seconds",        # newest applied event: now - publish ts
        "event_backlog",            # latest kv-event queue depth
        "router_degraded",          # 1 while in stale-snapshot degraded mode
        "router_degraded_entries",  # cumulative degraded-mode entries
    )

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        for name in self.FIELDS:
            setattr(self, name, 0)

    def snapshot(self) -> dict:
        return {name: getattr(self, name) for name in self.FIELDS}


CP_STATS = ControlPlaneStats()
