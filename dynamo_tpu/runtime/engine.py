"""Core engine abstraction: streaming generate with a controllable context.

The reference's central trait is AsyncEngine: `generate(SingleIn<Req>) ->
ManyOut<Resp>` with a per-request AsyncEngineContext carrying id/stop/kill
(reference: lib/runtime/src/engine.rs:22-110, pipeline/context.rs:33-160).
Python/asyncio equivalent: `generate(request, Context) -> AsyncIterator`.
"""
from __future__ import annotations

import abc
import asyncio
import time
import uuid
import weakref
from typing import Any, AsyncIterator, Dict, Optional

from dynamo_tpu.runtime.tracing import TRACE_KEY, TraceContext


class Context:
    """Request envelope: id, typed baggage, cooperative stop/kill signals,
    and an optional end-to-end deadline.

    stop = "finish the current response gracefully and end the stream";
    kill = "abandon immediately" — the same split as the reference's
    AsyncEngineContext stop_generating/kill (reference:
    lib/runtime/src/engine.rs:47-85).

    The deadline is an absolute time.monotonic() instant; it crosses
    process boundaries as *remaining seconds* (component.Client.generate
    ships `deadline_s`, the serving side rebuilds a local absolute
    deadline), so clocks never need to agree.
    """

    def __init__(self, request_id: Optional[str] = None,
                 baggage: Optional[Dict[str, Any]] = None,
                 deadline_s: Optional[float] = None):
        self.id = request_id or uuid.uuid4().hex
        self.baggage: Dict[str, Any] = dict(baggage or {})
        # trace context (runtime/tracing.py): rides baggage under
        # TRACE_KEY, so it crosses the wire with the dispatch envelope
        # and re-hydrates here on the serving side. None when the
        # request is untraced (tracing disabled, or a bare Context).
        self.trace: Optional[TraceContext] = (
            TraceContext.from_wire(self.baggage.get(TRACE_KEY))
            if self.baggage else None)
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()
        self._deadline: Optional[float] = None
        self._children: "weakref.WeakSet[Context]" = weakref.WeakSet()
        if deadline_s is not None:
            self.set_deadline(deadline_s)

    # -- control -------------------------------------------------------------
    def stop_generating(self) -> None:
        self._stopped.set()
        for c in list(self._children):
            c.stop_generating()

    def kill(self) -> None:
        self._killed.set()
        self._stopped.set()
        for c in list(self._children):
            c.kill()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def wait_stopped(self) -> None:
        await self._stopped.wait()

    # -- deadline ------------------------------------------------------------
    def set_deadline(self, timeout_s: float) -> None:
        """Arm (or tighten) the end-to-end deadline: timeout_s from now."""
        dl = time.monotonic() + timeout_s
        if self._deadline is None or dl < self._deadline:
            self._deadline = dl

    @property
    def deadline(self) -> Optional[float]:
        """Absolute time.monotonic() deadline, or None when unbounded."""
        return self._deadline

    def time_remaining(self) -> Optional[float]:
        """Seconds left before the deadline (>= 0), None when unbounded."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    @property
    def deadline_expired(self) -> bool:
        return self._deadline is not None \
            and time.monotonic() >= self._deadline

    def child(self) -> "Context":
        """Same id + baggage + deadline, linked cancellation: a parent
        stop/kill cascades into every live child (children are held
        weakly, so an abandoned child never leaks)."""
        c = Context(self.id, self.baggage)
        c._deadline = self._deadline
        if c.trace is None:
            c.trace = self.trace  # programmatic trace not yet in baggage
        if self.is_stopped:
            c._stopped.set()
        if self.is_killed:
            c._killed.set()
        self._children.add(c)
        return c


class AsyncEngine(abc.ABC):
    """A streaming request->response engine."""

    @abc.abstractmethod
    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        """Return an async iterator of response frames."""


class FnEngine(AsyncEngine):
    """Wrap an async generator function as an engine (test fixture pattern,
    reference: lib/runtime/tests/common/engines.rs closure engines)."""

    def __init__(self, fn):
        self._fn = fn

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._fn(request, context)
