"""Core engine abstraction: streaming generate with a controllable context.

The reference's central trait is AsyncEngine: `generate(SingleIn<Req>) ->
ManyOut<Resp>` with a per-request AsyncEngineContext carrying id/stop/kill
(reference: lib/runtime/src/engine.rs:22-110, pipeline/context.rs:33-160).
Python/asyncio equivalent: `generate(request, Context) -> AsyncIterator`.
"""
from __future__ import annotations

import abc
import asyncio
import uuid
from typing import Any, AsyncIterator, Dict, Optional


class Context:
    """Request envelope: id, typed baggage, cooperative stop/kill signals.

    stop = "finish the current response gracefully and end the stream";
    kill = "abandon immediately" — the same split as the reference's
    AsyncEngineContext stop_generating/kill (reference:
    lib/runtime/src/engine.rs:47-85).
    """

    def __init__(self, request_id: Optional[str] = None,
                 baggage: Optional[Dict[str, Any]] = None):
        self.id = request_id or uuid.uuid4().hex
        self.baggage: Dict[str, Any] = dict(baggage or {})
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()

    # -- control -------------------------------------------------------------
    def stop_generating(self) -> None:
        self._stopped.set()

    def kill(self) -> None:
        self._killed.set()
        self._stopped.set()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def wait_stopped(self) -> None:
        await self._stopped.wait()

    def child(self) -> "Context":
        """Same id + baggage, linked cancellation (parent stop cascades)."""
        c = Context(self.id, self.baggage)
        if self.is_stopped:
            c._stopped.set()
        if self.is_killed:
            c._killed.set()
        return c


class AsyncEngine(abc.ABC):
    """A streaming request->response engine."""

    @abc.abstractmethod
    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        """Return an async iterator of response frames."""


class FnEngine(AsyncEngine):
    """Wrap an async generator function as an engine (test fixture pattern,
    reference: lib/runtime/tests/common/engines.rs closure engines)."""

    def __init__(self, fn):
        self._fn = fn

    def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        return self._fn(request, context)
