"""Deadline helper: bound awaits by explicit timeouts and Context deadlines.

Every control-plane or transport await in the serving path must be bounded
(dynalint R7): an unbounded await on a dead peer turns one lost worker into
a wedged frontend. `with_deadline` is the sanctioned wrapper — it combines
an operation-level timeout with whatever remains of the request's
end-to-end deadline (runtime/engine.Context.set_deadline), whichever is
tighter, and raises DeadlineExceeded when the request-level budget is
already spent.
"""
from __future__ import annotations

import asyncio
from typing import Optional


class DeadlineExceeded(asyncio.TimeoutError):
    """The request's end-to-end deadline expired (distinct from a single
    operation timing out, which may be retried within the deadline)."""


def effective_timeout(timeout_s: Optional[float],
                      context=None) -> Optional[float]:
    """Tighter of an operation timeout and the context's remaining budget.

    Returns None when neither bounds the await. Raises DeadlineExceeded
    when the context deadline is already spent — callers should not start
    work they have no budget to finish.
    """
    remaining = context.time_remaining() if context is not None else None
    if remaining is not None and remaining <= 0:
        raise DeadlineExceeded("request deadline exceeded")
    candidates = [t for t in (timeout_s, remaining) if t is not None]
    return min(candidates) if candidates else None


async def with_deadline(awaitable, timeout_s: Optional[float] = None,
                        context=None):
    """Await `awaitable` bounded by timeout_s and/or the context deadline.

    asyncio.TimeoutError propagates from the operation timeout;
    DeadlineExceeded (a TimeoutError subclass) when the request-level
    deadline is what expired.
    """
    try:
        eff = effective_timeout(timeout_s, context)
    except DeadlineExceeded:
        # callers build the coroutine as an argument; close it so an
        # already-spent deadline doesn't spam "never awaited" warnings
        if asyncio.iscoroutine(awaitable):
            awaitable.close()
        raise
    if eff is None:
        return await awaitable
    try:
        return await asyncio.wait_for(awaitable, eff)
    except asyncio.TimeoutError:
        if context is not None and context.deadline_expired:
            raise DeadlineExceeded("request deadline exceeded") from None
        raise
