"""Discoverable component model: Namespace -> Component -> Endpoint -> Client.

Same tree and discovery semantics as the reference (reference:
lib/runtime/src/component.rs:99-270, component/endpoint.rs:57-144,
component/client.rs:52-245): an endpoint instance registers a KV key
`{ns}/components/{comp}/{endpoint}:{worker_id}` under the worker's primary
lease and serves the request subject `{ns}|{comp}.{endpoint}-{worker_id}`;
clients watch the KV prefix to track live instances and route
random / round-robin / direct.
"""
from __future__ import annotations

import asyncio
import json
import logging
import random
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

import msgpack

from dynamo_tpu.runtime import dataplane
from dynamo_tpu.runtime.backoff import Backoff
from dynamo_tpu.runtime.cpstats import CP_STATS
from dynamo_tpu.runtime.deadline import with_deadline
from dynamo_tpu.runtime.engine import AsyncEngine, Context, FnEngine
from dynamo_tpu.runtime.tracing import TRACER

log = logging.getLogger("dynamo_tpu.component")

# upper bound on waiting for a dispatch ack (the request-plane round trip;
# response frames ride the data plane with their own inactivity handling) —
# tightened further by the request Context's deadline when one is armed
DISPATCH_ACK_TIMEOUT_S = 30.0

# instance lifecycle states carried in the instance-key JSON ("status").
# READY is implicit (absent == ready, so pre-drain registrations need no
# migration); DRAINING = planned maintenance: routers stop NEW
# assignments, in-flight streams finish within the drain deadline or
# migrate via the reliability layer (docs/RESILIENCE.md runbook).
STATUS_READY = "ready"
STATUS_DRAINING = "draining"


class DrainStats:
    """Process-local drain counters (/metrics: llm_drain_*)."""

    def __init__(self):
        self.drains_started = 0
        self.drains_completed = 0
        self.drained_streams = 0       # finished within the deadline
        self.cancelled_streams = 0     # cut at the deadline (migrate)

    def snapshot(self):
        return dict(self.__dict__)


DRAIN_STATS = DrainStats()


def instance_status(info: Optional[Dict[str, Any]]) -> str:
    """Lifecycle status of an instance-key value (absent => ready)."""
    if not info:
        return STATUS_READY
    return info.get("status", STATUS_READY)


def instance_role(info: Optional[Dict[str, Any]]) -> Optional[str]:
    """Serving role carried in the instance-key JSON ("role":
    "prefill"/"decode" on disaggregated fleets). Absent => None: a
    role-less instance serves everything (aggregated fleets need no
    migration), so role-filtered callers treat None as wildcard."""
    if not info:
        return None
    return info.get("role")


def instance_key(ns: str, comp: str, endpoint: str, worker_id: str) -> str:
    return f"{ns}/components/{comp}/{endpoint}:{worker_id}"


def instance_subject(ns: str, comp: str, endpoint: str, worker_id: str) -> str:
    return f"{ns}|{comp}.{endpoint}-{worker_id}"


class DecodedSubscription:
    """msgpack-decoding view over a transport subscription stream that
    PRESERVES the batching surface (next_batch/depth/aclose) — the
    kv_router's event pump needs per-tick batches and the live backlog
    for its lag/backpressure accounting, which a plain decoding
    generator would hide."""

    def __init__(self, raw):
        self._raw = raw

    def __aiter__(self):
        return self

    async def __anext__(self):
        subj, payload = await self._raw.__anext__()
        return subj, msgpack.unpackb(payload, raw=False)

    async def next_batch(self, max_items: int = 4096,
                         timeout: Optional[float] = None) -> list:
        nb = getattr(self._raw, "next_batch", None)
        if nb is None:   # plain async-gen transport: batches of one
            batch = [await self._raw.__anext__()]
        else:
            batch = await nb(max_items, timeout)
        return [(s, msgpack.unpackb(p, raw=False)) for s, p in batch]

    def depth(self) -> int:
        d = getattr(self._raw, "depth", None)
        return d() if d is not None else 0

    async def aclose(self) -> None:
        a = getattr(self._raw, "aclose", None)
        if a is not None:
            await a()


class Namespace:
    def __init__(self, runtime, name: str):
        self._rt = runtime
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._rt, self, name)

    # -- event plane (reference: lib/runtime/src/traits/events.rs:27-79) -----
    def event_subject(self, subject: str) -> str:
        return f"{self.name}.{subject}"

    async def publish(self, subject: str, payload: Any) -> None:
        await self._rt.messaging.publish(
            self.event_subject(subject), msgpack.packb(payload))

    async def subscribe(self, subject: str):
        return DecodedSubscription(await self._rt.messaging.subscribe(
            self.event_subject(subject)))


class Component:
    def __init__(self, runtime, namespace: Namespace, name: str):
        self._rt = runtime
        self.namespace = namespace
        self.name = name

    @property
    def etcd_root(self) -> str:
        return f"{self.namespace.name}/components/{self.name}"

    @property
    def service_name(self) -> str:
        return f"{self.namespace.name}|{self.name}"

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._rt, self, name)

    async def publish(self, subject: str, payload: Any) -> None:
        await self._rt.messaging.publish(
            f"{self.namespace.name}.{self.name}.{subject}", msgpack.packb(payload))

    async def subscribe(self, subject: str):
        return DecodedSubscription(await self._rt.messaging.subscribe(
            f"{self.namespace.name}.{self.name}.{subject}"))

    async def list_instances(self) -> List[Dict[str, Any]]:
        entries = await self._rt.kv.get_prefix(self.etcd_root + "/")
        out = []
        for e in entries:
            try:
                out.append(json.loads(e.value))
            except (ValueError, TypeError):
                continue
        return out


class Endpoint:
    def __init__(self, runtime, component: Component, name: str):
        self._rt = runtime
        self.component = component
        self.name = name

    @property
    def ns(self) -> str:
        return self.component.namespace.name

    def key_for(self, worker_id: str) -> str:
        return instance_key(self.ns, self.component.name, self.name, worker_id)

    def subject_for(self, worker_id: str) -> str:
        return instance_subject(self.ns, self.component.name, self.name, worker_id)

    async def serve(
        self,
        engine: AsyncEngine | Callable,
        metadata: Optional[Dict[str, Any]] = None,
        stats_handler: Optional[Callable[[], Dict[str, Any]]] = None,
    ) -> "ServedEndpoint":
        """Register and start serving this endpoint instance.

        The handler runs the push-endpoint loop (reference:
        pipeline/network/ingress/push_handler.rs:25-112): decode the request
        envelope, rebuild the Context, call home over TCP, run the engine,
        pump response frames into the socket.
        """
        if not isinstance(engine, AsyncEngine):
            engine = FnEngine(engine)
        rt = self._rt
        worker_id = rt.worker_id
        subject = self.subject_for(worker_id)
        # live response-pump tasks, engine-agnostic: graceful drain
        # (llm/worker.install_graceful_drain) waits for this to empty
        inflight: set = set()

        async def handle(payload: bytes) -> bytes:
            env = msgpack.unpackb(payload, raw=False)
            ctx = Context(env.get("request_id"), env.get("baggage") or {})
            # deadlines cross the wire as remaining seconds (clocks differ)
            if env.get("deadline_s") is not None:
                ctx.set_deadline(float(env["deadline_s"]))
            try:
                reader_writer = await dataplane.call_home(
                    env["connection_info"], env["stream_id"], ctx)
            except Exception as e:
                return msgpack.packb({"ok": False, "error": str(e)})
            _, writer = reader_writer
            req = msgpack.unpackb(env["payload"], raw=False)

            async def run():
                # worker-side stream span: one per served dispatch, any
                # engine type. The wire-carried trace parents it under
                # the dispatching attempt span; re-parenting ctx nests
                # everything the engine records (disagg child spans,
                # decode.emit instants) under this worker span.
                span = TRACER.begin_span("worker.generate", ctx.trace,
                                         request_id=ctx.id,
                                         subject=subject)
                if span is not None:
                    ctx.trace = span.context()
                failed = True
                try:
                    try:
                        gen = engine.generate(req, ctx)
                    except Exception as e:  # engine rejected outright
                        log.exception("engine failure on %s", subject)
                        await dataplane.close_with_error(
                            writer, f"{type(e).__name__}: {e}")
                        return
                    # generator-time failures forwarded by pump_stream
                    await dataplane.pump_stream(writer, _packed(gen), ctx)
                    failed = False
                finally:
                    TRACER.end_span(span, error=failed)

            task = asyncio.create_task(run())
            inflight.add(task)
            task.add_done_callback(inflight.discard)
            return msgpack.packb({"ok": True})

        unserve = await rt.messaging.serve(subject, handle)
        info = {
            "namespace": self.ns,
            "component": self.component.name,
            "endpoint": self.name,
            "worker_id": worker_id,
            "subject": subject,
            **(metadata or {}),
        }
        await rt.kv.put(self.key_for(worker_id), json.dumps(info).encode(),
                        rt.lease.id if rt.lease else 0)
        served = ServedEndpoint(self, worker_id, unserve, stats_handler,
                                inflight=inflight, info=info)
        rt.register_served(served)
        if stats_handler is not None:
            stats_subject = f"$STATS.{subject}"
            async def stats(payload: bytes) -> bytes:
                return msgpack.packb(stats_handler())
            served._unserve_stats = await rt.messaging.serve(stats_subject, stats)
        return served

    def client(self) -> "Client":
        return Client(self._rt, self)


def _packed(gen) -> AsyncIterator[bytes]:
    async def inner():
        async for item in gen:
            yield msgpack.packb(item)
    return inner()


class ServedEndpoint:
    def __init__(self, endpoint: Endpoint, worker_id: str, unserve,
                 stats_handler=None, inflight: set = None, info=None):
        self.endpoint = endpoint
        self.worker_id = worker_id
        self._unserve = unserve
        self._unserve_stats = None
        self.stats_handler = stats_handler
        # live response pumps (graceful drain waits on this emptying)
        self.inflight: set = inflight if inflight is not None else set()
        self.info: Dict[str, Any] = dict(info or {})
        self._shut = False
        self.draining = False

    async def mark_draining(self) -> None:
        """Flip this instance to DRAINING: the instance key is re-put
        with status=draining (same lease), so every watching client and
        router fences it out of NEW assignments while the request
        subject stays up for in-flight streams."""
        self.draining = True
        rt = self.endpoint._rt
        info = {**self.info, "status": STATUS_DRAINING}
        await rt.kv.put(self.endpoint.key_for(self.worker_id),
                        json.dumps(info).encode(),
                        rt.lease.id if rt.lease else 0)

    async def drain(self, timeout_s: float = 30.0,
                    poll_s: float = 0.05,
                    force: Optional[Callable[[], bool]] = None) -> dict:
        """Zero-drop maintenance shutdown of this instance.

        1. mark DRAINING (routers stop picking it — kv_router fences its
           indexer entries, clients drop it from selection);
        2. wait up to timeout_s for in-flight response streams to finish
           (`force()` returning True skips the wait — the double-SIGTERM
           operator escalation);
        3. cancel whatever is left — the client side sees the stream cut
           WITHOUT a finish frame and the reliability layer migrates it,
           committed prefix intact (token-identical, docs/RESILIENCE.md);
        4. deregister + unserve (shutdown()).

        Returns a summary dict; counters land on DRAIN_STATS.
        """
        DRAIN_STATS.drains_started += 1
        started_with = len(self.inflight)
        try:
            await self.mark_draining()
        except Exception:  # dynalint: swallow-ok=drain-proceeds-without-fence
            log.exception("drain: marking %s draining failed; "
                          "draining anyway", self.worker_id)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while self.inflight and loop.time() < deadline \
                and not (force is not None and force()):
            await asyncio.sleep(poll_s)
        cancelled = len(self.inflight)
        for task in list(self.inflight):
            # the cut stream migrates via the reliability layer; a raw
            # client sees a reset, same as a worker death
            task.cancel()
        DRAIN_STATS.drained_streams += max(0, started_with - cancelled)
        DRAIN_STATS.cancelled_streams += cancelled
        if cancelled:
            log.warning("drain %s: %d stream(s) cut at the deadline "
                        "(migrating)", self.worker_id, cancelled)
        await self.shutdown()
        DRAIN_STATS.drains_completed += 1
        return {"worker_id": self.worker_id, "inflight_at_start":
                started_with, "cancelled": cancelled}

    async def re_role(self, role: str, drain_timeout_s: float = 30.0,
                      poll_s: float = 0.05) -> dict:
        """Re-register this LIVE instance under a new serving role —
        the real-worker leg of the autoscaler's "this decode worker
        becomes a prefill worker" actuation (runtime/autoscaler.py).

        Fence ordering (the drain-vs-schedule race guard): the
        instance key is re-put with status=DRAINING first, so every
        watching client/router drops it from `ids_for_role(old_role)`
        the moment that event is applied; then in-flight response
        streams get up to `drain_timeout_s` to finish (they are NOT
        cancelled — a role change is planned maintenance of the
        routing table, not of the streams); only then does the ready
        re-put with the NEW role land. Between the two puts the
        instance is schedulable for NEITHER role, so old-role work can
        never race onto a worker that has already re-roled.
        """
        rt = self.endpoint._rt
        from_role = self.info.get("role")
        started_with = len(self.inflight)
        try:
            await self.mark_draining()
        except Exception:  # dynalint: swallow-ok=re-role-proceeds-without-fence
            log.exception("re_role: marking %s draining failed; "
                          "re-roling anyway", self.worker_id)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + drain_timeout_s
        while self.inflight and loop.time() < deadline:
            await asyncio.sleep(poll_s)
        lingering = len(self.inflight)
        if lingering:
            log.warning("re_role %s: %d stream(s) still in flight at "
                        "the drain deadline; re-roling around them",
                        self.worker_id, lingering)
        self.info["role"] = role
        self.info.pop("status", None)
        self.draining = False
        await rt.kv.put(self.endpoint.key_for(self.worker_id),
                        json.dumps(self.info).encode(),
                        rt.lease.id if rt.lease else 0)
        return {"worker_id": self.worker_id, "from_role": from_role,
                "to_role": role, "inflight_at_start": started_with,
                "lingering": lingering}

    async def shutdown(self):
        # idempotent (drain calls it, then runtime.shutdown sweeps all
        # served endpoints again) and ordered: the instance KEY goes
        # first so watching routers stop picking this instance BEFORE the
        # request subject disappears — the other order hard-fails any
        # request racing the drain with "no responder"
        if self._shut:
            return
        self._shut = True
        await self.endpoint._rt.kv.delete(self.endpoint.key_for(self.worker_id))
        await self._unserve()
        if self._unserve_stats is not None:
            await self._unserve_stats()


class Client:
    """Routes requests to live endpoint instances.

    Maintains a watch on the instance prefix (reference:
    component/client.rs:64-149) and offers random / round_robin / direct
    routing (reference: client.rs:181-244) plus the streaming request path
    over the data plane.
    """

    def __init__(self, runtime, endpoint: Endpoint):
        self._rt = runtime
        self.endpoint = endpoint
        self.instances: Dict[str, Dict[str, Any]] = {}
        self._rr = 0
        self._watch_task: Optional[asyncio.Task] = None
        self._ready = asyncio.Event()
        # instance-change listeners: cb(kind, worker_id, info) fired on
        # every watch event AS IT ARRIVES — the kv_router evicts a dead
        # worker's indexer entries here, immediately, instead of waiting
        # for the next metrics scrape to notice (a dead worker's cached-
        # prefix score otherwise keeps attracting routes until the
        # circuit breaker trips)
        self._listeners: List[Callable[[str, str, Optional[dict]], None]] \
            = []
        # cached ready/draining id lists: at 1000 instances a sorted
        # full-fleet scan per schedule() call was a superlinear hot path
        # (the router consults draining_ids on EVERY request); the cache
        # invalidates on watch events, which is the only way state moves
        self._ids_dirty = True
        self._ready_cache: List[str] = []
        self._draining_cache: List[str] = []
        # per-role dispatchable ids (re-role fence reads; role-less
        # instances are wildcards kept separately so aggregated fleets
        # answer every role without per-call scans)
        self._role_cache: Dict[str, List[str]] = {}
        self._roleless_cache: List[str] = []

    def add_listener(self,
                     cb: Callable[[str, str, Optional[dict]], None]) -> None:
        """Register cb(kind, worker_id, info); kind is "put"/"delete".
        Called synchronously from the watch pump — keep it cheap."""
        self._listeners.append(cb)

    async def start(self) -> "Client":
        self._prefix = instance_key(self.endpoint.ns,
                                    self.endpoint.component.name,
                                    self.endpoint.name, "")
        snapshot, stream = await self._rt.kv.watch_prefix(self._prefix)
        for e in snapshot:
            self._apply("put", e.key, e.value)
        self._ready.set()
        self._watch_task = asyncio.create_task(self._watch_loop(stream))
        return self

    async def _watch_loop(self, stream) -> None:
        """Watch pump: applies events in per-tick BATCHES (a churn storm
        of N events on one key costs one listener pass, not N), and on
        watch-stream failure resumes with bounded backoff + jitter and a
        full snapshot resync — a watcher may die, it must never die
        SILENTLY (the pre-storm pump did exactly that: one exception and
        the client served stale instances forever)."""
        backoff = Backoff(base_s=0.05, max_s=2.0, stable_reset_s=10.0)
        try:
            while True:
                try:
                    batch = await stream.next_batch()
                    CP_STATS.watch_queue_depth = stream.depth()
                    self._apply_batch(batch)
                    backoff.reset()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.warning("instance watch for %s failed; resuming "
                                "with resync", self._prefix, exc_info=True)
                    try:
                        await stream.aclose()
                    except Exception:  # dynalint: swallow-ok=old-stream-best-effort-close
                        pass
                    await backoff.sleep()
                    try:
                        snapshot, stream = await self._rt.kv.watch_prefix(
                            self._prefix)
                    except Exception:  # dynalint: swallow-ok=store-unavailable-window-retried-next-backoff-round
                        log.warning("watch re-establish failed for %s",
                                    self._prefix, exc_info=True)
                        continue
                    CP_STATS.watch_resyncs += 1
                    self._resync(snapshot)
        finally:
            try:
                await stream.aclose()
            except Exception:  # dynalint: swallow-ok=teardown-best-effort-close
                pass

    def _apply_batch(self, events) -> None:
        """Coalesce a tick's events per key (last state wins — put→delete
        applies only the delete, flap→final applies only the final) and
        apply once per key. Different keys are independent instance
        states, so cross-key order is immaterial."""
        if not events:
            return
        final: Dict[str, Any] = {}
        for ev in events:
            final[ev.key] = ev
        CP_STATS.watch_events_applied += len(final)
        CP_STATS.watch_events_coalesced += len(events) - len(final)
        for ev in final.values():
            self._apply(ev.kind, ev.key, ev.value)

    def _resync(self, snapshot) -> None:
        """Reconcile full state after a watch gap: deletes missed while
        the stream was down MUST still fire listeners — the kv_router's
        dead-worker eviction fence hangs off them."""
        seen = set()
        for e in snapshot:
            seen.add(e.key.rsplit(":", 1)[-1])
            self._apply("put", e.key, e.value)
        gone = [w for w in self.instances if w not in seen]
        for worker_id in gone:
            self._apply("delete", self.endpoint.key_for(worker_id), None)
        # resync-recovered state counts as applied events (they replace
        # the deliveries lost with the dead stream)
        CP_STATS.watch_events_applied += len(snapshot) + len(gone)

    def _apply(self, kind: str, key: str, value: Optional[bytes]):
        worker_id = key.rsplit(":", 1)[-1]
        info: Optional[Dict[str, Any]] = None
        if kind == "put" and value is not None:
            try:
                info = json.loads(value)
            except (ValueError, TypeError):
                return
            self.instances[worker_id] = info
        elif kind == "delete":
            self.instances.pop(worker_id, None)
        self._ids_dirty = True
        for cb in self._listeners:
            try:
                cb(kind, worker_id, info)
            except Exception:  # dynalint: swallow-ok=listener-fault-must-not-kill-watch
                log.exception("instance listener failed for %s", worker_id)

    async def wait_for_instances(self, timeout: float = 10.0) -> None:
        deadline = asyncio.get_running_loop().time() + timeout
        while not self.instances:
            if asyncio.get_running_loop().time() > deadline:
                raise TimeoutError(
                    f"no instances of {self.endpoint.subject_for('*')}")
            await asyncio.sleep(0.02)

    def _recompute_ids(self) -> None:
        ready: List[str] = []
        draining: List[str] = []
        roleless: List[str] = []
        by_role: Dict[str, List[str]] = {}
        for w in sorted(self.instances):
            info = self.instances[w]
            if instance_status(info) == STATUS_DRAINING:
                draining.append(w)
                continue
            ready.append(w)
            role = instance_role(info)
            if role is not None:
                by_role.setdefault(role, []).append(w)
            else:
                roleless.append(w)
        self._ready_cache, self._draining_cache = ready, draining
        self._role_cache = by_role
        self._roleless_cache = roleless
        self._ids_dirty = False

    def instance_ids(self, include_draining: bool = False) -> List[str]:
        """Dispatchable instance ids. DRAINING instances are excluded —
        planned maintenance must attract no new assignments — UNLESS
        every live instance is draining (a probe on a draining-but-alive
        worker beats failing the request outright, the same fallback
        shape as the circuit breaker's all-ejected case). Returns the
        watch-maintained cache: callers must not mutate it."""
        if include_draining:
            return sorted(self.instances)
        if self._ids_dirty:
            self._recompute_ids()
        return self._ready_cache if self._ready_cache \
            else sorted(self.instances)

    def draining_ids(self) -> List[str]:
        if self._ids_dirty:
            self._recompute_ids()
        return self._draining_cache

    def ids_for_role(self, role: str) -> List[str]:
        """Dispatchable instance ids serving `role` — the re-role fence:
        a worker flipped to DRAINING (the first leg of
        `ServedEndpoint.re_role` / `SimWorker.set_role`) leaves this
        list the moment its watch event is APPLIED, and only re-enters
        under its NEW role's list with the ready re-put, so there is no
        window where old-role work can schedule onto it. Role-less
        instances (aggregated fleets) count for every role. Returns
        the watch-maintained cache: callers must not mutate it."""
        if self._ids_dirty:
            self._recompute_ids()
        declared = self._role_cache.get(role, [])
        if not self._roleless_cache:
            return declared
        if not declared:
            return self._roleless_cache
        return sorted(declared + self._roleless_cache)

    # -- routing -------------------------------------------------------------

    def _pick_random(self) -> str:
        return random.choice(self.instance_ids())

    def _pick_round_robin(self) -> str:
        ids = self.instance_ids()
        self._rr = (self._rr + 1) % len(ids)
        return ids[self._rr]

    async def generate(self, request: Any, context: Optional[Context] = None,
                       instance: Optional[str] = None,
                       policy: str = "random") -> AsyncIterator[Any]:
        """Send a request; yields response frames (decoded msgpack)."""
        if not self.instances:
            await self.wait_for_instances()
        if instance is None:
            instance = (self._pick_round_robin() if policy == "round_robin"
                        else self._pick_random())
        ctx = context or Context()
        subject = self.endpoint.subject_for(instance)

        server = await self._rt.data_plane()
        stream = server.register()
        envelope = msgpack.packb({
            "request_id": ctx.id,
            "baggage": ctx.baggage,
            "payload": msgpack.packb(request),
            "connection_info": server.connection_info,
            "stream_id": stream.stream_id,
            "deadline_s": ctx.time_remaining(),
        })
        try:
            ack = msgpack.unpackb(
                await with_deadline(
                    self._rt.messaging.request(
                        subject, envelope, timeout=DISPATCH_ACK_TIMEOUT_S),
                    DISPATCH_ACK_TIMEOUT_S, ctx),
                raw=False)
        except Exception:
            server.unregister(stream.stream_id)
            raise
        if not ack.get("ok"):
            server.unregister(stream.stream_id)
            raise RuntimeError(ack.get("error", "request rejected"))

        async def gen():
            stopped = False
            async for data in server.stream_responses(stream):
                if ctx.is_stopped and not stopped:
                    stopped = True
                    await server.send_stop(stream)
                    if ctx.is_killed:
                        return
                yield msgpack.unpackb(data, raw=False)

        return gen()

    async def direct(self, request: Any, instance: str,
                     context: Optional[Context] = None) -> AsyncIterator[Any]:
        return await self.generate(request, context, instance=instance)

    async def round_robin(self, request: Any,
                          context: Optional[Context] = None) -> AsyncIterator[Any]:
        return await self.generate(request, context, policy="round_robin")

    async def scrape_stats(self, timeout: float = 2.0) -> Dict[str, Dict]:
        """Collect custom stats from each live instance (reference:
        NATS $SRV.STATS scrape, lib/runtime/src/service.rs:32-100).

        Instances are scraped concurrently: the whole cycle costs one
        timeout regardless of fleet size, so a dead instance can't add
        its 2 s to every aggregator interval (VERDICT r2 weak #7).
        """
        async def one(worker_id: str):
            subject = f"$STATS.{self.endpoint.subject_for(worker_id)}"
            try:
                raw = await self._rt.messaging.request(subject, b"",
                                                       timeout=timeout)
                return worker_id, msgpack.unpackb(raw, raw=False)
            except Exception:
                return worker_id, None

        results = await asyncio.gather(*(one(w) for w in self.instance_ids()))
        return {w: stats for w, stats in results if stats is not None}

    async def stop(self):
        if self._watch_task:
            self._watch_task.cancel()
