"""Seeded bounded backoff with jitter + flap hysteresis.

Every control-plane retry loop (watch-pump resume, re-registration after
a lease loss, breaker re-probe) needs the same three properties, and a
1000-worker storm punishes any loop missing one of them:

- **bounded exponential growth**: a persistent outage must not tighten
  into a busy-loop against the discovery store;
- **jitter**: when hundreds of workers lose their leases in one burst,
  un-jittered backoff re-synchronizes them into repeated thundering
  herds — every retry wave lands on the store at the same instant
  (dynalint R12 enforces that the loops in-tree carry this);
- **flap hysteresis**: a worker that keeps cycling register → die →
  register within a short window should wait LONGER each cycle, but one
  that has been stable for a while earns a fresh (short) first delay.
  ``stable_reset_s`` implements this: the attempt counter only rewinds
  after the loop has gone that long without asking for a delay.

Seeded (`rng`) so the sim harness's storms are replayable: the same
seed yields the same jitter sequence.
"""
from __future__ import annotations

import asyncio
import random
import time
from typing import Optional


class Backoff:
    """Delay source for one retry loop. Not thread-safe (asyncio-owned).

    ``next_delay()`` grows ``base_s * 2**k`` capped at ``max_s``, with
    multiplicative jitter in ``[1, 1+jitter]`` (the reliability layer's
    shape); ``reset()`` rewinds after a confirmed success. Hysteresis:
    if ``stable_reset_s`` elapsed since the last ``next_delay()`` call,
    the counter rewinds on its own — a flap burst keeps growing delays,
    a stable stretch forgives them.
    """

    def __init__(self, base_s: float = 0.05, max_s: float = 5.0,
                 jitter: float = 0.5, stable_reset_s: float = 30.0,
                 rng: Optional[random.Random] = None):
        self.base_s = base_s
        self.max_s = max_s
        self.jitter = jitter
        self.stable_reset_s = stable_reset_s
        self._rng = rng or random.Random()
        self._attempts = 0
        self._last_ask: Optional[float] = None

    @property
    def attempts(self) -> int:
        return self._attempts

    def next_delay(self) -> float:
        now = time.monotonic()
        if (self._last_ask is not None and self.stable_reset_s > 0
                and now - self._last_ask > self.stable_reset_s):
            self._attempts = 0
        self._last_ask = now
        delay = min(self.max_s, self.base_s * (2 ** self._attempts))
        self._attempts += 1
        return delay * (1.0 + self.jitter * self._rng.random())

    async def sleep(self) -> float:
        delay = self.next_delay()
        await asyncio.sleep(delay)
        return delay

    def reset(self) -> None:
        self._attempts = 0


def jittered(delay_s: float, jitter: float = 0.5,
             rng: Optional[random.Random] = None) -> float:
    """One-shot jittered delay (re-registration staggering: N workers
    restarting after a storm must not stampede discovery in one tick)."""
    r = rng or random
    return delay_s * (1.0 + jitter * r.random())
