"""Composable streaming pipeline graph: Source -> Operator* -> Sink.

The reference's runtime composes request/response flows from typed
pipeline nodes — a frontend Source, chainable Operators (preprocessor,
backend, routers), and an engine Sink — wired with `link()` and rewired
dynamically when discovery adds or removes engines (reference:
lib/runtime/src/pipeline/nodes.rs:72-209 and the SDK's dynamic
`.link()` composition, deploy/dynamo/sdk/src/dynamo/sdk/lib/service.py:173).
This is the asyncio restatement: every node speaks the AsyncEngine calling
convention (`generate(request, context) -> async iterator`), an Operator
additionally receives the downstream node, and a Segment is the linked
chain — callable like any engine, introspectable, and rewirable in place
(`segment.set_sink(...)`) without rebuilding upstream state.

    seg = source(preprocess_op).link(router_op).link(engine_sink)
    async for frame in seg.generate(req, ctx): ...
    seg.set_sink(new_engine_sink)        # hot-swap on discovery change

llm/pipeline.py builds the model-serving flow from these nodes; the SDK's
`Service.link()` uses the same left-to-right linking convention for
deployment graphs.
"""
from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Callable, List, Optional

__all__ = ["Sink", "Operator", "FnSink", "FnOperator", "Segment", "source"]


class Sink(abc.ABC):
    """Terminal node: produces the response stream (an engine)."""

    @abc.abstractmethod
    def generate(self, request: Any, context: Any) -> AsyncIterator:
        ...


class Operator(abc.ABC):
    """Intermediate node: transforms the request and/or response stream,
    delegating to `downstream` (itself a Sink-shaped node)."""

    @abc.abstractmethod
    def generate(self, request: Any, context: Any,
                 downstream: Sink) -> AsyncIterator:
        ...


class FnSink(Sink):
    """Adapt any `async gen fn(request, context)` (or AsyncEngine-shaped
    object) into a Sink node."""

    def __init__(self, fn: Callable[[Any, Any], AsyncIterator]):
        self._fn = fn

    def generate(self, request, context):
        return self._fn(request, context)


class FnOperator(Operator):
    def __init__(self, fn: Callable[[Any, Any, Sink], AsyncIterator]):
        self._fn = fn

    def generate(self, request, context, downstream):
        return self._fn(request, context, downstream)


class _Tail(Sink):
    """Downstream view of a segment from operator position i+1 onward."""

    def __init__(self, segment: "Segment", pos: int):
        self._segment = segment
        self._pos = pos

    def generate(self, request, context):
        return self._segment._dispatch(self._pos, request, context)


class Segment(Sink):
    """A linked Source->Operator*->Sink chain; itself a Sink, so segments
    nest. Operators run outermost-first; `set_sink`/`set_operator` rewire
    the live graph (new requests see the new wiring; in-flight streams
    keep the nodes they captured)."""

    def __init__(self, operators: Optional[List[Operator]] = None,
                 sink: Optional[Sink] = None):
        self.operators: List[Operator] = list(operators or [])
        self.sink = sink

    # -- composition ---------------------------------------------------------

    def link(self, node) -> "Segment":
        """Append a node; Operators extend the chain, a Sink (or async-gen
        callable) terminates it. Returns self for `a.link(b).link(c)`."""
        if isinstance(node, Operator):
            self.operators.append(node)
        elif isinstance(node, Sink):
            if self.sink is not None:
                raise ValueError("segment already has a sink; use "
                                 "set_sink() to replace it")
            self.sink = node
        elif callable(node):
            return self.link(FnSink(node))
        else:
            raise TypeError(f"cannot link {node!r}: expected Operator, "
                            f"Sink, or async-gen callable")
        return self

    def set_sink(self, sink) -> None:
        """Dynamic rewiring: replace the terminal engine (discovery swap)."""
        self.sink = sink if isinstance(sink, Sink) else FnSink(sink)

    def set_operator(self, pos: int, op: Operator) -> None:
        self.operators[pos] = op

    # -- execution -----------------------------------------------------------

    def _dispatch(self, pos: int, request, context) -> AsyncIterator:
        if pos < len(self.operators):
            return self.operators[pos].generate(request, context,
                                                _Tail(self, pos + 1))
        if self.sink is None:
            raise RuntimeError("segment has no sink linked")
        return self.sink.generate(request, context)

    def generate(self, request, context) -> AsyncIterator:
        return self._dispatch(0, request, context)


def source(*nodes) -> Segment:
    """Start a segment, optionally linking initial nodes."""
    seg = Segment()
    for n in nodes:
        seg.link(n)
    return seg
