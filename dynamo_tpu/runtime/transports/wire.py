"""Length-prefixed msgpack framing shared by the control plane and data plane.

Equivalent in role to the reference's TwoPartCodec length-prefixed wire format
(reference: lib/runtime/src/pipeline/network/codec/two_part.rs:23-139); we use
a single msgpack map per frame (header fields + binary payload under "payload")
rather than separate header/data parts — msgpack keeps binary payloads
zero-escape, and one map keeps the codec trivial.
"""
from __future__ import annotations

import asyncio
import struct
from typing import Any, Dict, Optional

import msgpack

MAX_FRAME = 256 * 1024 * 1024  # KV pages can be large


def pack(msg: Dict[str, Any]) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return struct.pack(">I", len(body)) + body


async def _read_frame_inner(reader: asyncio.StreamReader) -> Dict[str, Any]:
    # dynalint: unbounded-io-ok=bounded by read_frame(timeout=) or the caller's wrapper
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds max {MAX_FRAME}")
    # dynalint: unbounded-io-ok=bounded by read_frame(timeout=) or the caller's wrapper
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


async def read_frame(reader: asyncio.StreamReader,
                     timeout: Optional[float] = None) -> Dict[str, Any]:
    """Read one frame. `timeout` (seconds) bounds the WHOLE frame —
    header and body together, so a peer that trickles bytes cannot
    stretch one read past the deadline. None = caller owns the bound
    (an idle server-side pump, or an enclosing wait_for)."""
    if timeout is None:
        return await _read_frame_inner(reader)
    return await asyncio.wait_for(_read_frame_inner(reader), timeout)


def write_frame(writer: asyncio.StreamWriter, msg: Dict[str, Any]) -> None:
    writer.write(pack(msg))


async def oneshot_request(host: str, port: int, msg: Dict[str, Any],
                          timeout: float = 5.0, keep_open: bool = False):
    """Open a connection, send one id-tagged frame, await the matching
    reply. Shared by role probes and HA fencing (tcp._probe_role,
    server._primary_alive, server._fence_peer). Both the connect and the
    reply read sit under `timeout`, so a blackholed or wedged peer costs
    seconds, not the OS connect timeout's minutes. With keep_open=True
    returns (reply, reader, writer) for the caller to adopt as a live
    connection; otherwise closes and returns the reply alone."""
    async def _go():
        # the whole _go() body (connect included) runs under the single
        # wait_for(timeout) below
        # dynalint: disable-next-line=R7
        reader, writer = await asyncio.open_connection(host, port)
        try:
            write_frame(writer, {"id": 1, **msg})
            # dynalint: unbounded-io-ok=whole-_go-body-under-one-wait_for
            await writer.drain()
            while True:
                # dynalint: unbounded-io-ok=whole-_go-body-under-one-wait_for
                m = await read_frame(reader)
                if m.get("id") == 1:
                    return m, reader, writer
        except BaseException:  # incl. the deadline's CancelledError
            writer.close()
            raise

    # ONE deadline spans connect + request + reply (a peer that accepts
    # slowly and then never replies costs `timeout` total, not 2x)
    reply, reader, writer = await asyncio.wait_for(_go(), timeout)
    if keep_open:
        return reply, reader, writer
    writer.close()
    return reply
