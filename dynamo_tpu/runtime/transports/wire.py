"""Length-prefixed msgpack framing shared by the control plane and data plane.

Equivalent in role to the reference's TwoPartCodec length-prefixed wire format
(reference: lib/runtime/src/pipeline/network/codec/two_part.rs:23-139); we use
a single msgpack map per frame (header fields + binary payload under "payload")
rather than separate header/data parts — msgpack keeps binary payloads
zero-escape, and one map keeps the codec trivial.
"""
from __future__ import annotations

import asyncio
import struct
from typing import Any, Dict

import msgpack

MAX_FRAME = 256 * 1024 * 1024  # KV pages can be large


def pack(msg: Dict[str, Any]) -> bytes:
    body = msgpack.packb(msg, use_bin_type=True)
    return struct.pack(">I", len(body)) + body


async def read_frame(reader: asyncio.StreamReader) -> Dict[str, Any]:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds max {MAX_FRAME}")
    body = await reader.readexactly(length)
    return msgpack.unpackb(body, raw=False)


def write_frame(writer: asyncio.StreamWriter, msg: Dict[str, Any]) -> None:
    writer.write(pack(msg))
