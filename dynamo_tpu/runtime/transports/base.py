"""Control-plane transport interfaces.

The reference splits its control plane between etcd (discovery/config/lease,
reference: lib/runtime/src/transports/etcd.rs) and NATS (request plane,
events, work queue, reference: transports/nats.rs). We keep the same
*semantics* behind two interfaces — KVStore and Messaging — with two
implementations: an in-process memory plane (test + single-process serving,
the analogue of the reference's mock network, reference:
lib/runtime/tests/common/mock.rs) and a TCP client to our standalone
control-plane server (dynamo_tpu.runtime.transports.server).
"""
from __future__ import annotations

import abc
import dataclasses
from typing import AsyncIterator, Awaitable, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class WatchEvent:
    kind: str          # "put" | "delete"
    key: str
    value: Optional[bytes] = None


@dataclasses.dataclass
class KVEntry:
    key: str
    value: bytes
    lease_id: int = 0


class Lease:
    """A TTL lease; keys attached to it vanish when it expires/revokes.

    Matches the reference's primary-lease semantics: lease lost => runtime
    shutdown; shutdown => lease revoked (reference: transports/etcd.rs:85-120,
    etcd/lease.rs). TTL default 10s per BASELINE.md.
    """

    def __init__(self, lease_id: int, revoke_cb):
        self.id = lease_id
        self._revoke_cb = revoke_cb
        self.lost = None  # set by transport: asyncio.Event fired on expiry

    async def revoke(self):
        await self._revoke_cb(self.id)


class KVStore(abc.ABC):
    """etcd-role: consistent KV with atomic create, prefix watch, leases."""

    @abc.abstractmethod
    async def put(self, key: str, value: bytes, lease_id: int = 0) -> None: ...

    @abc.abstractmethod
    async def create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        """Atomic create; False if the key already exists."""

    @abc.abstractmethod
    async def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    async def get_prefix(self, prefix: str) -> List[KVEntry]: ...

    @abc.abstractmethod
    async def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    async def grant_lease(self, ttl: float = 10.0) -> Lease: ...

    @abc.abstractmethod
    async def watch_prefix(
        self, prefix: str
    ) -> Tuple[List[KVEntry], AsyncIterator[WatchEvent]]:
        """Snapshot + subsequent events (reference: etcd.rs
        kv_get_and_watch_prefix)."""


Handler = Callable[[bytes], Awaitable[AsyncIterator[bytes]]]


class Messaging(abc.ABC):
    """NATS-role: addressed request/reply, pub/sub events, durable queue."""

    @abc.abstractmethod
    async def serve(self, subject: str,
                    handler: Callable[[bytes], Awaitable[bytes]]) -> Callable:
        """Register a request handler; returns an async unsubscribe fn."""

    @abc.abstractmethod
    async def request(self, subject: str, payload: bytes,
                      timeout: float = 30.0) -> bytes: ...

    @abc.abstractmethod
    async def publish(self, subject: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def subscribe(self, subject: str) -> AsyncIterator[Tuple[str, bytes]]:
        """Subscribe to a subject (trailing '>' wildcard supported)."""

    @abc.abstractmethod
    async def queue_push(self, queue: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def queue_pop(self, queue: str,
                        timeout: Optional[float] = None) -> Optional[bytes]:
        """Durable work-queue pop (reference: NATS JetStream prefill queue)."""

    @abc.abstractmethod
    async def queue_depth(self, queue: str) -> int: ...

    # -- leased consumption (JetStream ack/redelivery semantics) --------------
    # Default implementations degrade to plain pop with a no-op ack, so a
    # Messaging backend without lease support still serves consumers that
    # speak the leased protocol — they just lose redelivery on crash.

    async def queue_pop_leased(
            self, queue: str, timeout: Optional[float] = None,
            lease_s: float = 30.0) -> Optional[Tuple[bytes, str]]:
        """Pop one item under a redelivery lease.

        Returns (payload, lease_token) or None on timeout. An item popped
        but not queue_ack'ed within lease_s is re-enqueued (the consumer
        died mid-item — reference: JetStream ack-wait redelivery), up to a
        backend-defined redelivery cap, after which it is dropped and
        logged (poison-message protection)."""
        payload = await self.queue_pop(queue, timeout=timeout)
        return None if payload is None else (payload, "")

    async def queue_ack(self, queue: str, token: str) -> None:
        """Settle a leased item: it is done (or terminally failed) and must
        not be redelivered."""


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style: '>' matches any suffix."""
    if pattern.endswith(">"):
        return subject.startswith(pattern[:-1])
    return pattern == subject
