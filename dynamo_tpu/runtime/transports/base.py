"""Control-plane transport interfaces.

The reference splits its control plane between etcd (discovery/config/lease,
reference: lib/runtime/src/transports/etcd.rs) and NATS (request plane,
events, work queue, reference: transports/nats.rs). We keep the same
*semantics* behind two interfaces — KVStore and Messaging — with two
implementations: an in-process memory plane (test + single-process serving,
the analogue of the reference's mock network, reference:
lib/runtime/tests/common/mock.rs) and a TCP client to our standalone
control-plane server (dynamo_tpu.runtime.transports.server).
"""
from __future__ import annotations

import abc
import asyncio
import dataclasses
import inspect
from typing import AsyncIterator, Awaitable, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class WatchEvent:
    kind: str          # "put" | "delete"
    key: str
    value: Optional[bytes] = None


@dataclasses.dataclass
class KVEntry:
    key: str
    value: bytes
    lease_id: int = 0


class Lease:
    """A TTL lease; keys attached to it vanish when it expires/revokes.

    Matches the reference's primary-lease semantics: lease lost => runtime
    shutdown; shutdown => lease revoked (reference: transports/etcd.rs:85-120,
    etcd/lease.rs). TTL default 10s per BASELINE.md.
    """

    def __init__(self, lease_id: int, revoke_cb):
        self.id = lease_id
        self._revoke_cb = revoke_cb
        self.lost = None  # set by transport: asyncio.Event fired on expiry

    async def revoke(self):
        await self._revoke_cb(self.id)


class QueueStream:
    """Async-iterable delivery stream over a transport queue.

    Both transports used to hand consumers a bare async generator over
    an asyncio.Queue; at cluster scale that shape has three gaps this
    class closes:

    - ``next_batch()``: await the first item, then drain everything
      already queued — a churn storm costs ONE consumer wakeup and one
      application pass per tick instead of one per event (the watch /
      event-plane coalescing the 1000-worker sim demands);
    - ``depth()``: the live backlog, for the ``llm_cp_*`` queue-depth
      gauges and the router's backpressure signal;
    - ``aclose()``: deterministic teardown (the generators relied on GC
      finalization to run their ``finally`` blocks).

    ``failpoint``: an optional faults.py site evaluated once per
    ``__anext__``/``next_batch`` delivery; an injected drop raises
    ``FaultInjected`` into the consumer — the stream-disconnect model.
    Consumers that must survive it (Client/ModelWatcher watch pumps)
    resume with backoff + snapshot resync; items lost with the
    disconnect are recovered by that resync.
    """

    def __init__(self, queue: asyncio.Queue,
                 on_close: Optional[Callable] = None,
                 failpoint: Optional[str] = None):
        self._q = queue
        self._on_close = on_close
        self._failpoint = failpoint
        self._closed = False

    def _fire(self) -> None:
        if self._failpoint is None:
            return
        from dynamo_tpu.runtime import faults
        if not faults.REGISTRY.enabled:
            return
        out = faults.REGISTRY.decide(self._failpoint)
        if out is not None and out.drop:
            raise faults.FaultInjected(self._failpoint)

    def __aiter__(self):
        return self

    async def __anext__(self):
        item = await self._q.get()
        self._fire()
        return item

    async def next_batch(self, max_items: int = 4096,
                         timeout: Optional[float] = None) -> list:
        """Await the first item, then drain whatever is already queued
        (up to ``max_items``). Returns ``[]`` on timeout when one is
        given — consumers use that to run idle-time checks (degraded-
        mode exit, lag decay) without a second timer task."""
        try:
            if timeout is None:
                first = await self._q.get()
            else:
                first = await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return []
        batch = [first]
        while len(batch) < max_items:
            try:
                batch.append(self._q.get_nowait())
            except asyncio.QueueEmpty:
                break
        self._fire()
        return batch

    def depth(self) -> int:
        return self._q.qsize()

    async def aclose(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._on_close is not None:
            res = self._on_close()
            if inspect.isawaitable(res):
                await res


class WatchStream(QueueStream):
    """KV watch-event delivery; carries the ``watch.stream`` failpoint
    (an injected drop == the watch stream disconnecting mid-flight)."""

    def __init__(self, queue: asyncio.Queue,
                 on_close: Optional[Callable] = None):
        super().__init__(queue, on_close, failpoint="watch.stream")


class SubscriptionStream(QueueStream):
    """Event-plane delivery of (subject, payload) pairs. Lag/reorder/
    drop faults are injected on the PUBLISH side (the event.plane site),
    where a delayed delivery can actually arrive out of order."""


class KVStore(abc.ABC):
    """etcd-role: consistent KV with atomic create, prefix watch, leases."""

    @abc.abstractmethod
    async def put(self, key: str, value: bytes, lease_id: int = 0) -> None: ...

    @abc.abstractmethod
    async def create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        """Atomic create; False if the key already exists."""

    @abc.abstractmethod
    async def get(self, key: str) -> Optional[bytes]: ...

    @abc.abstractmethod
    async def get_prefix(self, prefix: str) -> List[KVEntry]: ...

    @abc.abstractmethod
    async def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    async def grant_lease(self, ttl: float = 10.0) -> Lease: ...

    @abc.abstractmethod
    async def watch_prefix(
        self, prefix: str
    ) -> Tuple[List[KVEntry], AsyncIterator[WatchEvent]]:
        """Snapshot + subsequent events (reference: etcd.rs
        kv_get_and_watch_prefix)."""


Handler = Callable[[bytes], Awaitable[AsyncIterator[bytes]]]


class Messaging(abc.ABC):
    """NATS-role: addressed request/reply, pub/sub events, durable queue."""

    @abc.abstractmethod
    async def serve(self, subject: str,
                    handler: Callable[[bytes], Awaitable[bytes]]) -> Callable:
        """Register a request handler; returns an async unsubscribe fn."""

    @abc.abstractmethod
    async def request(self, subject: str, payload: bytes,
                      timeout: float = 30.0) -> bytes: ...

    @abc.abstractmethod
    async def publish(self, subject: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def subscribe(self, subject: str) -> AsyncIterator[Tuple[str, bytes]]:
        """Subscribe to a subject (trailing '>' wildcard supported)."""

    @abc.abstractmethod
    async def queue_push(self, queue: str, payload: bytes) -> None: ...

    @abc.abstractmethod
    async def queue_pop(self, queue: str,
                        timeout: Optional[float] = None) -> Optional[bytes]:
        """Durable work-queue pop (reference: NATS JetStream prefill queue)."""

    @abc.abstractmethod
    async def queue_depth(self, queue: str) -> int: ...

    # -- leased consumption (JetStream ack/redelivery semantics) --------------
    # Default implementations degrade to plain pop with a no-op ack, so a
    # Messaging backend without lease support still serves consumers that
    # speak the leased protocol — they just lose redelivery on crash.

    async def queue_pop_leased(
            self, queue: str, timeout: Optional[float] = None,
            lease_s: float = 30.0) -> Optional[Tuple[bytes, str]]:
        """Pop one item under a redelivery lease.

        Returns (payload, lease_token) or None on timeout. An item popped
        but not queue_ack'ed within lease_s is re-enqueued (the consumer
        died mid-item — reference: JetStream ack-wait redelivery), up to a
        backend-defined redelivery cap, after which it is dropped and
        logged (poison-message protection)."""
        payload = await self.queue_pop(queue, timeout=timeout)
        return None if payload is None else (payload, "")

    async def queue_ack(self, queue: str, token: str) -> None:
        """Settle a leased item: it is done (or terminally failed) and must
        not be redelivered."""

    async def queue_touch(self, queue: str, token: str,
                          lease_s: float = 30.0) -> bool:
        """Extend a leased item's redelivery deadline to now + lease_s
        (JetStream in-progress ack): a consumer entering a long leg it
        is still actively driving (a resumable KV transfer) re-arms the
        lease instead of sizing lease_s for the worst case up front.
        Returns False when the lease is unknown — already expired and
        redelivered, so the caller's work is now a duplicate. Default:
        no-op success (lease-less backends)."""
        return True


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style: '>' matches any suffix."""
    if pattern.endswith(">"):
        return subject.startswith(pattern[:-1])
    return pattern == subject
