"""TCP client for the control-plane server: KVStore + Messaging over one socket.

Counterpart of the reference's etcd/NATS client wrappers (reference:
lib/runtime/src/transports/etcd.rs:38-328, transports/nats.rs:45-110) — one
multiplexed connection carries KV ops, watches, addressed requests, events,
and queue ops.
"""
from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Dict, Optional

from dynamo_tpu.runtime.transports.base import (
    KVEntry, KVStore, Lease, Messaging, SubscriptionStream, WatchEvent,
    WatchStream,
)
from dynamo_tpu.runtime.transports.wire import (
    oneshot_request, read_frame, write_frame,
)

log = logging.getLogger("dynamo_tpu.transports.tcp")


class ControlPlaneClient(KVStore, Messaging):
    def __init__(self, host: str = "127.0.0.1", port: int = 6230,
                 addrs=None):
        """addrs: optional [(host, port), ...] — an HA control-plane pair;
        connect() probes roles and follows whichever member is primary
        (VERDICT r3 missing #3 failover). Fencing (VERDICT r4 #4): the
        probe collects every reachable member's promotion epoch, enrolls
        with the HIGHEST-epoch primary, and echoes that epoch on every
        subsequent op — so a deposed primary that survived a partition is
        either refused (our epoch is older: we re-probe) or deposed on
        contact (our epoch is newer: it steps down)."""
        self.host, self.port = host, port
        self.addrs = list(addrs) if addrs else [(host, port)]
        self.epoch: Optional[int] = None
        self._reader = None
        self._writer = None
        self._ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._watch_queues: Dict[int, asyncio.Queue] = {}
        self._sub_queues: Dict[int, asyncio.Queue] = {}
        self._handlers: Dict[str, callable] = {}
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_tasks: Dict[int, asyncio.Task] = {}
        self._write_lock = asyncio.Lock()
        self.closed = asyncio.Event()

    async def connect(self, timeout_s: float = 20.0) -> "ControlPlaneClient":
        """Connect to the primary member of `addrs`, retrying until the
        deadline: a dead member is skipped, a standby is probed (role op)
        and skipped, and a mid-failover window (old primary dead, standby
        not yet promoted) is ridden out by the retry loop. With several
        primaries visible (partition aftermath) the HIGHEST promotion
        epoch wins — the deposed side is never enrolled with. The winning
        probe connection is adopted as the client connection (one dial
        per member per round, no redial)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        last_err: Optional[Exception] = None
        while True:
            best = None  # (epoch, host, port, reader, writer)
            for host, port in self.addrs:
                try:
                    info, reader, writer = await oneshot_request(
                        host, port, {"op": "role"}, 5.0, keep_open=True)
                except Exception as e:  # noqa: BLE001 — try the next member
                    last_err = e
                    continue
                role = info.get("role", "primary")
                if role == "primary":
                    cand = (info.get("epoch", 1), host, port, reader, writer)
                    if best is None or cand[0] > best[0]:
                        if best is not None:
                            best[4].close()
                        best = cand
                        continue
                else:
                    last_err = ConnectionError(f"{host}:{port} is {role}")
                writer.close()
            if best is not None:
                epoch, host, port, reader, writer = best
                self._reader, self._writer = reader, writer
                self._reader_task = asyncio.create_task(self._read_loop())
                self.host, self.port, self.epoch = host, port, epoch
                return self
            if loop.time() >= deadline:
                raise ConnectionError(
                    f"no primary control plane among {self.addrs}"
                ) from last_err
            await asyncio.sleep(0.5)

    async def close(self):
        for t in self._keepalive_tasks.values():
            t.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()
        self.closed.set()

    # -- plumbing ------------------------------------------------------------

    async def _send(self, msg):
        from dynamo_tpu.runtime import faults
        if faults.REGISTRY.enabled:   # drop => ConnectionError (FaultInjected)
            await faults.REGISTRY.fire("transport.send")
        async with self._write_lock:
            write_frame(self._writer, msg)
            # bounded: a control-plane peer that stops reading must not
            # wedge every sender behind the write lock. TimeoutError is
            # an OSError (3.11+), so existing transport-death handlers
            # treat it as a lost connection.
            await asyncio.wait_for(self._writer.drain(), 30.0)

    async def _rpc(self, msg, timeout: float = 60.0):
        rid = next(self._ids)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            # every op echoes the enrolled promotion epoch (fencing): the
            # server refuses older-epoch ops and steps down on newer ones
            if self.epoch is not None and "epoch" not in msg:
                msg = {"epoch": self.epoch, **msg}
            await self._send({"id": rid, **msg})
            reply = await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(rid, None)
        if reply.get("error"):
            raise RuntimeError(reply["error"])
        return reply

    async def _read_loop(self):
        try:
            while True:
                # dynalint: unbounded-io-ok=idle-is-legal-here — the server
                # pushes watch/sub events at arbitrary times; liveness is
                # the keepalive loop's job, death surfaces as EOF
                msg = await read_frame(self._reader)
                op = msg.get("op")
                if op is None:
                    fut = self._pending.get(msg.get("id"))
                    if fut is not None and not fut.done():
                        fut.set_result(msg)
                elif op == "watch_event":
                    q = self._watch_queues.get(msg["watch_id"])
                    if q:
                        q.put_nowait(WatchEvent(msg["kind"], msg["key"],
                                                msg.get("value")))
                elif op == "event":
                    q = self._sub_queues.get(msg["sub_id"])
                    if q:
                        q.put_nowait((msg["subject"], msg["payload"]))
                elif op == "handle":
                    asyncio.create_task(self._handle_request(msg))
        except (asyncio.IncompleteReadError, ConnectionResetError,
                asyncio.CancelledError):
            pass
        finally:
            self.closed.set()
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("control plane lost"))

    async def _handle_request(self, msg):
        handler = self._handlers.get(msg["subject"])
        reply = {"op": "reply", "handle_id": msg["handle_id"]}
        if handler is None:
            reply["error"] = f"no local handler for {msg['subject']!r}"
        else:
            try:
                reply["payload"] = await handler(msg["payload"])
            except Exception as e:  # noqa: BLE001 — reported to the caller
                reply["error"] = f"{type(e).__name__}: {e}"
        await self._send(reply)

    # -- KVStore -------------------------------------------------------------

    async def put(self, key, value, lease_id: int = 0):
        await self._rpc({"op": "put", "key": key, "value": value,
                         "lease": lease_id})

    async def create(self, key, value, lease_id: int = 0) -> bool:
        return (await self._rpc({"op": "create", "key": key, "value": value,
                                 "lease": lease_id}))["ok"]

    async def get(self, key):
        return (await self._rpc({"op": "get", "key": key}))["value"]

    async def get_prefix(self, prefix):
        reply = await self._rpc({"op": "get_prefix", "prefix": prefix})
        return [KVEntry(k, v, l) for k, v, l in reply["entries"]]

    async def delete(self, key):
        await self._rpc({"op": "delete", "key": key})

    async def grant_lease(self, ttl: float = 10.0) -> Lease:
        reply = await self._rpc({"op": "lease_grant", "ttl": ttl})
        lease_id = reply["lease"]
        lease = Lease(lease_id, self._revoke_lease)
        lease.lost = asyncio.Event()
        self._keepalive_tasks[lease_id] = asyncio.create_task(
            self._keepalive_loop(lease_id, ttl, lease))
        return lease

    async def _revoke_lease(self, lease_id: int):
        t = self._keepalive_tasks.pop(lease_id, None)
        if t:
            t.cancel()
        await self._rpc({"op": "lease_revoke", "lease": lease_id})

    async def _keepalive_loop(self, lease_id: int, ttl: float, lease: Lease):
        """Heartbeat at ttl/3; a lost lease fires lease.lost (the runtime
        couples that to shutdown, as the reference couples its primary etcd
        lease to the cancellation token)."""
        from dynamo_tpu.runtime import faults
        try:
            # dynalint: backoff-ok=TTL-paced lease renewal; cadence is ttl/3 by protocol, and a failed keepalive ends the loop (lease lost) instead of retrying hot
            while True:
                await asyncio.sleep(ttl / 3)
                if faults.REGISTRY.enabled:
                    try:
                        await faults.REGISTRY.fire("discovery.heartbeat")
                    except faults.FaultInjected:
                        continue  # this heartbeat round is lost
                try:
                    ok = (await self._rpc({"op": "lease_keepalive",
                                           "lease": lease_id}, timeout=ttl))["ok"]
                except Exception:
                    ok = False
                if not ok:
                    lease.lost.set()
                    return
        except asyncio.CancelledError:
            pass

    async def watch_prefix(self, prefix):
        reply = await self._rpc({"op": "watch", "prefix": prefix})
        wid = reply["watch_id"]
        q: asyncio.Queue = asyncio.Queue()
        self._watch_queues[wid] = q
        snapshot = [KVEntry(k, v, l) for k, v, l in reply["entries"]]

        async def on_close():
            self._watch_queues.pop(wid, None)
            try:
                await self._rpc({"op": "unwatch", "watch_id": wid})
            except Exception:  # dynalint: swallow-ok=best-effort-unwatch-on-close
                pass

        return snapshot, WatchStream(q, on_close=on_close)

    # -- Messaging -----------------------------------------------------------

    async def serve(self, subject, handler):
        self._handlers[subject] = handler
        await self._rpc({"op": "serve", "subject": subject})

        async def unsubscribe():
            self._handlers.pop(subject, None)
            await self._rpc({"op": "unserve", "subject": subject})

        return unsubscribe

    async def request(self, subject, payload, timeout: float = 30.0):
        reply = await self._rpc({"op": "request", "subject": subject,
                                 "payload": payload, "timeout": timeout},
                                timeout=timeout + 5)
        return reply["payload"]

    async def publish(self, subject, payload):
        await self._rpc({"op": "publish", "subject": subject,
                         "payload": payload})

    async def subscribe(self, subject):
        reply = await self._rpc({"op": "subscribe", "subject": subject})
        sid = reply["sub_id"]
        q: asyncio.Queue = asyncio.Queue()
        self._sub_queues[sid] = q

        async def on_close():
            self._sub_queues.pop(sid, None)
            try:
                await self._rpc({"op": "unsubscribe", "sub_id": sid})
            except Exception:  # dynalint: swallow-ok=best-effort-unsubscribe-on-close
                pass

        return SubscriptionStream(q, on_close=on_close)

    async def queue_push(self, queue, payload):
        await self._rpc({"op": "queue_push", "queue": queue,
                         "payload": payload})

    async def queue_pop(self, queue, timeout=None):
        rpc_timeout = (timeout + 5) if timeout is not None else 3600.0
        reply = await self._rpc({"op": "queue_pop", "queue": queue,
                                 "timeout": timeout}, timeout=rpc_timeout)
        return reply["payload"]

    async def queue_pop_leased(self, queue, timeout=None, lease_s=30.0):
        rpc_timeout = (timeout + 5) if timeout is not None else 3600.0
        reply = await self._rpc(
            {"op": "queue_pop_leased", "queue": queue, "timeout": timeout,
             "lease_s": lease_s}, timeout=rpc_timeout)
        if reply.get("payload") is None:
            return None
        return reply["payload"], reply["token"]

    async def queue_ack(self, queue, token):
        await self._rpc({"op": "queue_ack", "queue": queue, "token": token})

    async def queue_touch(self, queue, token, lease_s: float = 30.0):
        reply = await self._rpc({"op": "queue_touch", "queue": queue,
                                 "token": token, "lease_s": lease_s})
        return bool(reply.get("alive", True))

    async def queue_depth(self, queue):
        return (await self._rpc({"op": "queue_depth", "queue": queue}))["depth"]
