"""In-process control plane: KVStore + Messaging backed by plain dicts.

The single-process analogue of etcd+NATS, in the spirit of the reference's
in-memory mock control/data plane used to test multi-component behavior
without a cluster (reference: lib/runtime/tests/common/mock.rs:31-60,
including its injectable LatencyModel).
"""
from __future__ import annotations

import asyncio
import itertools
import logging
import time
import uuid
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.transports.base import (
    KVEntry, KVStore, Lease, Messaging, SubscriptionStream, WatchEvent,
    WatchStream, subject_matches,
)

log = logging.getLogger("dynamo_tpu.memory_plane")


async def _lossy_fire(site: str):
    """Failpoint hook for fire-and-forget deliveries: a drop loses the
    message instead of raising (pub/sub has no error channel). Returns
    None when the message is lost, else the Outcome."""
    try:
        return await faults.REGISTRY.fire(site)
    except faults.FaultInjected:
        return None


class LatencyModel:
    """Optional injected delay for simulating network hops in tests."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s

    async def apply(self):
        if self.delay_s > 0:
            await asyncio.sleep(self.delay_s)


class MemoryKVStore(KVStore):
    def __init__(self, latency: Optional[LatencyModel] = None):
        self._data: Dict[str, KVEntry] = {}
        self._watchers: List[Tuple[str, asyncio.Queue]] = []
        self._lease_seq = itertools.count(1)
        self._lease_keys: Dict[int, set] = defaultdict(set)
        self._lease_tasks: Dict[int, asyncio.Task] = {}
        self._lease_deadline: Dict[int, float] = {}
        self._latency = latency or LatencyModel()

    def _data_restore(self, key: str, value: bytes) -> None:
        """Recovery-path set: no journaling, no watcher notify (recovery runs
        before any watcher can exist). Used by transports.journal."""
        self._data[key] = KVEntry(key, value, 0)

    def _data_drop(self, key: str) -> None:
        self._data.pop(key, None)

    async def _notify(self, ev: WatchEvent):
        for prefix, q in list(self._watchers):
            if ev.key.startswith(prefix):
                q.put_nowait(ev)

    async def put(self, key: str, value: bytes, lease_id: int = 0) -> None:
        await self._latency.apply()
        if faults.REGISTRY.enabled:   # drop => ConnectionError to caller
            await faults.REGISTRY.fire("transport.send")
            await faults.REGISTRY.fire("discovery.store")
        self._data[key] = KVEntry(key, value, lease_id)
        if lease_id:
            self._lease_keys[lease_id].add(key)
        await self._notify(WatchEvent("put", key, value))

    async def create(self, key: str, value: bytes, lease_id: int = 0) -> bool:
        await self._latency.apply()
        if key in self._data:
            return False
        await self.put(key, value, lease_id)
        return True

    async def get(self, key: str) -> Optional[bytes]:
        await self._latency.apply()
        if faults.REGISTRY.enabled:
            await faults.REGISTRY.fire("transport.send")
            await faults.REGISTRY.fire("discovery.store")
        e = self._data.get(key)
        return e.value if e else None

    async def get_prefix(self, prefix: str) -> List[KVEntry]:
        await self._latency.apply()
        if faults.REGISTRY.enabled:
            await faults.REGISTRY.fire("transport.send")
            await faults.REGISTRY.fire("discovery.store")
        return [e for k, e in sorted(self._data.items()) if k.startswith(prefix)]

    async def delete(self, key: str) -> None:
        await self._latency.apply()
        if faults.REGISTRY.enabled:
            await faults.REGISTRY.fire("transport.send")
            await faults.REGISTRY.fire("discovery.store")
        e = self._data.pop(key, None)
        if e is not None:
            if e.lease_id:
                self._lease_keys[e.lease_id].discard(key)
            await self._notify(WatchEvent("delete", key))

    # -- leases --------------------------------------------------------------

    async def grant_lease(self, ttl: float = 10.0) -> Lease:
        lease_id = next(self._lease_seq)
        lease = Lease(lease_id, self._revoke)
        lease.lost = asyncio.Event()
        self._lease_deadline[lease_id] = time.monotonic() + ttl
        self._lease_tasks[lease_id] = asyncio.create_task(
            self._lease_watchdog(lease_id, ttl, lease))
        lease.keep_alive = lambda: self._keep_alive(lease_id, ttl)
        return lease

    def _keep_alive(self, lease_id: int, ttl: float):
        if faults.REGISTRY.enabled:
            try:
                faults.REGISTRY.fire_sync("discovery.heartbeat")
            except faults.FaultInjected:
                return  # heartbeat lost: deadline not refreshed
        if lease_id in self._lease_deadline:
            self._lease_deadline[lease_id] = time.monotonic() + ttl

    async def _lease_watchdog(self, lease_id: int, ttl: float, lease: Lease):
        while True:
            deadline = self._lease_deadline.get(lease_id)
            if deadline is None:
                return
            now = time.monotonic()
            forced = False
            if faults.REGISTRY.enabled \
                    and faults.REGISTRY.armed("lease.expiry"):
                # lease-expiry burst site: a drop outcome force-expires
                # THIS lease now; armed with p over a fleet, each
                # watchdog tick expires ~p of the leases it visits
                out = faults.REGISTRY.decide("lease.expiry")
                forced = out is not None and out.drop
            if now >= deadline or forced:
                await self._expire(lease_id)
                lease.lost.set()
                return
            await asyncio.sleep(min(deadline - now, ttl / 3))

    async def _expire(self, lease_id: int):
        self._lease_deadline.pop(lease_id, None)
        for key in list(self._lease_keys.pop(lease_id, ())):
            await self.delete(key)

    async def _revoke(self, lease_id: int):
        task = self._lease_tasks.pop(lease_id, None)
        if task:
            task.cancel()
        await self._expire(lease_id)

    # -- watch ---------------------------------------------------------------

    async def watch_prefix(self, prefix: str):
        snapshot = await self.get_prefix(prefix)
        q: asyncio.Queue = asyncio.Queue()
        entry = (prefix, q)
        self._watchers.append(entry)

        def on_close():
            if entry in self._watchers:
                self._watchers.remove(entry)

        return snapshot, WatchStream(q, on_close=on_close)


class MemoryMessaging(Messaging):
    # redeliveries per item before it is dropped as poison
    MAX_REDELIVERIES = 5

    def __init__(self, latency: Optional[LatencyModel] = None):
        self._handlers: Dict[str, callable] = {}
        self._subs: List[Tuple[str, asyncio.Queue]] = []
        self._queues: Dict[str, asyncio.Queue] = defaultdict(asyncio.Queue)
        self._latency = latency or LatencyModel()
        # lease token -> (queue, payload, expiry_monotonic, prior_deliveries)
        self._leased: Dict[str, Tuple[str, bytes, float, int]] = {}
        # (queue, payload) -> redeliveries so far; survives pop/lease cycles
        # (the token is fresh per delivery) so poison items can't loop
        self._delivery_counts: Dict[Tuple[str, bytes], int] = {}
        self.redeliveries = 0  # observability: total re-enqueues

    async def serve(self, subject, handler):
        self._handlers[subject] = handler

        async def unsubscribe():
            if self._handlers.get(subject) is handler:
                del self._handlers[subject]

        return unsubscribe

    async def request(self, subject, payload, timeout: float = 30.0):
        await self._latency.apply()
        if faults.REGISTRY.enabled:   # drop => ConnectionError, retried by
            await faults.REGISTRY.fire("transport.send")  # reliability layer
        handler = self._handlers.get(subject)
        if handler is None:
            raise ConnectionError(f"no responder on subject {subject!r}")
        return await asyncio.wait_for(handler(payload), timeout)

    async def publish(self, subject, payload):
        await self._latency.apply()
        send_dup = False
        if faults.REGISTRY.enabled:
            out = await _lossy_fire("transport.send")
            if out is None:
                return  # event lost on the wire: fire-and-forget
            send_dup = out.duplicate
        for pattern, q in list(self._subs):
            if subject_matches(pattern, subject):
                if faults.REGISTRY.enabled:
                    out = await _lossy_fire("transport.recv")
                    if out is None:
                        continue  # lost for THIS subscriber only
                    dup = out.duplicate or send_dup
                    if not self._deliver_event_plane(q, subject, payload,
                                                     dup):
                        continue
                else:
                    q.put_nowait((subject, payload))

    @staticmethod
    def _deliver_event_plane(q, subject, payload, dup: bool) -> bool:
        """Per-subscriber delivery through the event.plane failpoint.
        Delay is applied via call_later — the delayed event arrives late
        AND after later undelayed events (lag ⇒ reorder, like a slow
        NATS consumer); drop loses it; duplicate doubles it. Returns
        False when the event was dropped."""
        out = (faults.REGISTRY.decide("event.plane")
               if faults.REGISTRY.armed("event.plane") else None)
        if out is not None and out.drop:
            return False
        copies = 2 if (dup or (out is not None and out.duplicate)) else 1
        if out is not None and out.delay_s > 0:
            loop = asyncio.get_running_loop()
            for _ in range(copies):
                loop.call_later(out.delay_s, q.put_nowait,
                                (subject, payload))
        else:
            for _ in range(copies):
                q.put_nowait((subject, payload))
        return True

    async def subscribe(self, subject):
        q: asyncio.Queue = asyncio.Queue()
        entry = (subject, q)
        self._subs.append(entry)

        def on_close():
            if entry in self._subs:
                self._subs.remove(entry)

        return SubscriptionStream(q, on_close=on_close)

    async def queue_push(self, queue, payload):
        await self._latency.apply()
        self._queues[queue].put_nowait(payload)

    async def queue_pop(self, queue, timeout=None):
        await self._sweep_leases()
        try:
            if timeout is None:
                return await self._queues[queue].get()
            return await asyncio.wait_for(self._queues[queue].get(), timeout)
        except asyncio.TimeoutError:
            return None

    async def queue_depth(self, queue):
        await self._sweep_leases()
        return self._queues[queue].qsize()

    # -- leases: dequeued-but-unacked items become visible again --------------
    # No background task: expiry is swept on every queue touch, which the
    # polling consumers (disagg PrefillWorker dequeue loop, disagg router
    # depth probes) provide; worst-case redelivery latency is one lease
    # window plus one consumer poll interval.

    async def _sweep_leases(self) -> None:
        if not self._leased:
            return
        now = time.monotonic()
        expired = [t for t, (_q, _p, dl, _n) in self._leased.items()
                   if dl <= now]
        for token in expired:
            queue, payload, _dl, n = self._leased.pop(token)
            if n + 1 > self.MAX_REDELIVERIES:
                log.error("queue %s: item dropped after %d redeliveries "
                          "(poison)", queue, n)
                self._delivery_counts.pop((queue, payload), None)
                continue
            self._delivery_counts[(queue, payload)] = n + 1
            self.redeliveries += 1
            log.warning("queue %s: lease expired, redelivering item "
                        "(delivery %d)", queue, n + 2)
            # rides the real push path so durable planes re-journal it
            await self.queue_push(queue, payload)

    async def queue_pop_leased(self, queue, timeout=None, lease_s=30.0):
        if timeout is None:
            # bounded slices instead of one unbounded get(): each slice
            # re-runs the lease sweep, so a lone blocked consumer still
            # sees items whose lease expired while it was waiting
            payload = None
            while payload is None:
                payload = await self.queue_pop(queue, timeout=1.0)
        else:
            payload = await self.queue_pop(queue, timeout=timeout)
        if payload is None:
            return None
        token = uuid.uuid4().hex
        self._leased[token] = (queue, payload,
                               time.monotonic() + lease_s,
                               self._delivery_counts.get((queue, payload), 0))
        return payload, token

    async def queue_ack(self, queue, token):
        item = self._leased.pop(token, None)
        if item is not None:
            self._delivery_counts.pop((item[0], item[1]), None)

    async def queue_touch(self, queue, token, lease_s: float = 30.0):
        item = self._leased.get(token)
        if item is None:
            # expired (and possibly already redelivered): the toucher's
            # copy of the work is now a duplicate
            return False
        q, payload, _deadline, n = item
        self._leased[token] = (q, payload, time.monotonic() + lease_s, n)
        return True


class MemoryPlane:
    """Bundle of both planes, shared by components within one process."""

    def __init__(self, latency: Optional[LatencyModel] = None):
        self.kv = MemoryKVStore(latency)
        self.messaging = MemoryMessaging(latency)
