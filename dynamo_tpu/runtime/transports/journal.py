"""Durability for the control-plane server: write-ahead journal + snapshot.

The reference outsources durability to etcd (raft-replicated KV) and NATS
JetStream (file-backed work queues) — SURVEY.md §L0, the prefill queue rides
JetStream precisely so queued work survives broker restarts
(reference: docs/disagg_serving.md:57-59). Our single-binary control plane
(transports/server.py) held everything in memory (ADVICE r2: non-durable
SPOF). This module adds the file-backed layer:

- DurablePlane wraps the in-memory plane and appends every *persistent*
  mutation to an append-only journal: unleased KV puts/deletes and work-queue
  push/pop. Lease-scoped keys are deliberately NOT persisted — as in etcd,
  a lease cannot outlive the server that granted it; workers re-register on
  reconnect (runtime/distributed.py lease keep-alive loop).
- Pub/sub events are fire-and-forget (NATS core semantics), never journaled.
- On open, state is rebuilt from the latest snapshot plus journal replay;
  when the journal exceeds `compact_every` records a fresh snapshot is
  written and the journal truncated (the JetStream file-store compaction
  analogue, scaled down).

Records are length-prefixed msgpack, crash-truncation-tolerant: a torn tail
record is discarded on replay.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import io
import logging
import os
import struct
from typing import Optional

import msgpack

from dynamo_tpu.runtime.transports.memory import (
    LatencyModel, MemoryKVStore, MemoryMessaging, MemoryPlane,
)

log = logging.getLogger("dynamo_tpu.journal")

_LEN = struct.Struct("<I")


def _append_record(f: io.BufferedWriter, rec: dict) -> None:
    payload = msgpack.packb(rec)
    f.write(_LEN.pack(len(payload)))
    f.write(payload)
    f.flush()


def _read_records(path: str):
    """Yield records; stop silently at a torn tail (crash mid-append)."""
    with open(path, "rb") as f:
        while True:
            head = f.read(_LEN.size)
            if len(head) < _LEN.size:
                return
            (n,) = _LEN.unpack(head)
            payload = f.read(n)
            if len(payload) < n:
                log.warning("journal %s: torn tail record dropped", path)
                return
            yield msgpack.unpackb(payload, raw=False)


class DurableKVStore(MemoryKVStore):
    def __init__(self, journal: "Journal",
                 latency: Optional[LatencyModel] = None):
        super().__init__(latency)
        self._journal = journal

    async def put(self, key, value, lease_id: int = 0):
        prev = self._data.get(key)
        await super().put(key, value, lease_id)
        if not lease_id:  # lease-scoped keys die with the server, as in etcd
            self._journal.append({"op": "put", "key": key, "value": value})
        elif prev is not None and not prev.lease_id:
            # a leased put shadowing a journaled unleased value: the old
            # value is gone for good (the key now dies with the lease), so
            # it must not resurrect from the journal after a restart
            self._journal.append({"op": "del", "key": key})

    async def delete(self, key):
        existed = key in self._data
        was_leased = existed and self._data[key].lease_id
        await super().delete(key)
        if existed and not was_leased:
            self._journal.append({"op": "del", "key": key})


class DurableMessaging(MemoryMessaging):
    def __init__(self, journal: "Journal",
                 latency: Optional[LatencyModel] = None):
        super().__init__(latency)
        self._journal = journal

    async def queue_push(self, queue, payload):
        await super().queue_push(queue, payload)
        # ack-after-durable: the server replies to queue_push only when
        # this coroutine returns, so awaiting the group-commit makes an
        # acknowledged push survive even a machine crash (VERDICT r3 #4;
        # JetStream file-store semantics, SURVEY §L0). Concurrent pushes
        # share one fsync via the writer thread's batch commit.
        fut = self._journal.append(
            {"op": "qpush", "queue": queue, "payload": payload}, ack=True)
        if fut is not None:
            await asyncio.wrap_future(fut)

    async def queue_pop(self, queue, timeout=None):
        item = await super().queue_pop(queue, timeout=timeout)
        if item is not None:
            # logged post-hoc: replay drops one head per qpop, so only the
            # surviving-queue *contents* must match, which FIFO guarantees
            self._journal.append({"op": "qpop", "queue": queue})
        return item


class Journal:
    """Append-only journal with snapshot compaction.

    Crash-atomicity across compaction (code-review r3): queue replay is not
    idempotent, so a crash between the snapshot rename and the journal
    truncation must not replay pre-compaction records on top of the new
    snapshot. Every fresh journal opens with a {"op": "jhead", "gen": G}
    record and the snapshot stores the generation it expects; recovery
    discards a journal whose generation doesn't match (it was already
    folded into the snapshot).

    Flush-behind writer thread (code-review r3): append() and compact()
    run on the control-plane event loop, so all file I/O — including the
    full snapshot rewrite — happens on a dedicated writer thread, in
    order. The loop only packs bytes and enqueues; a compaction never
    stalls leases/watches.

    Group-commit fsync (VERDICT r3 #4): with fsync=True (default) the
    writer drains every queued record, writes them, and fsyncs ONCE per
    batch — bounded latency under load, JetStream-file-store durability.
    append(rec, ack=True) returns a Future resolved only after that
    fsync, which queue_push awaits before the server acks: an
    acknowledged push survives OS/power crash, not just process crash.
    Fire-and-forget appends (KV puts) ride the same batches, so they are
    fsync'd too; only the ack path waits. A process crash can still lose
    enqueued-but-unwritten *unacked* records (never corrupting or
    reordering) — the same window the reference accepts via
    etcd/JetStream client-side buffering."""

    def __init__(self, data_dir: str, compact_every: int = 10_000,
                 fsync: bool = True):
        os.makedirs(data_dir, exist_ok=True)
        self.snap_path = os.path.join(data_dir, "snapshot.bin")
        self.journal_path = os.path.join(data_dir, "journal.bin")
        self.compact_every = compact_every
        self.fsync = fsync
        self._closed = False
        self._since_compact = 0
        self._gen = 0
        # fencing epoch (transports/ha): bumped on every standby promotion,
        # persisted here so a restarted member rejoins at the epoch it held.
        # 0 = never recorded (fresh data dir); the server treats that as 1.
        self.epoch = 0
        self._file: Optional[io.BufferedWriter] = None
        self._plane: Optional[MemoryPlane] = None
        import queue as _queue
        import threading as _threading
        self._q: "_queue.Queue" = _queue.Queue()
        # serializes append() against close(): without it a record can be
        # enqueued after the None sentinel (writer already stopping) and
        # silently never hit disk — with an ack future that never resolves
        self._close_lock = _threading.Lock()
        self._writer = _threading.Thread(
            target=self._writer_loop, name="cp-journal", daemon=True)
        self._writer.start()

    def attach(self, plane: MemoryPlane) -> None:
        self._plane = plane
        # replication tee (transports/ha role): called on the event-loop
        # side with every persistent-mutation record, in append order —
        # the hot-standby fanout point (server._fanout_record)
        self.on_record = None

    def append(self, rec: dict, ack: bool = False
               ) -> Optional[concurrent.futures.Future]:
        # the record carries the generation current at ENQUEUE time: the
        # writer stamps a fresh journal's jhead from it, so records
        # enqueued before a pending compaction never land under the new
        # generation (which would discard them on recovery)
        fut = concurrent.futures.Future() if ack else None
        with self._close_lock:
            # checked and enqueued under the same lock close() takes, so a
            # record can never slip in behind the shutdown sentinel (where
            # it would silently vanish and an ack future would never
            # resolve) — ADVICE r4. The replication tee lives under the
            # same gate: a record the closed journal refuses must not be
            # streamed to standbys either (they would journal a write the
            # primary never persisted — divergent histories).
            if self._closed:
                if fut is not None:
                    fut.set_exception(RuntimeError("journal is closed"))
                return fut
            tee = getattr(self, "on_record", None)
            if tee is not None:
                tee(rec)
            self._q.put(("rec", (msgpack.packb(rec), self._gen, fut)))
        self._since_compact += 1
        if self._since_compact >= self.compact_every:
            self.compact()
        return fut

    def sync(self) -> None:
        """Block until every enqueued write has reached the filesystem."""
        self._q.join()

    # -- writer thread --------------------------------------------------------

    def _writer_loop(self) -> None:
        import queue as _queue
        stop = False
        while not stop:
            # group-commit: take one item, then drain every immediately
            # available record so a burst shares a single fsync
            items = [self._q.get()]
            while items[-1] is not None and items[-1][0] == "rec":
                try:
                    items.append(self._q.get_nowait())
                except _queue.Empty:
                    break
            recs = [it[1] for it in items
                    if it is not None and it[0] == "rec"]
            tail = [it for it in items
                    if it is None or it[0] != "rec"]  # <=1 by construction
            if recs:
                try:
                    for payload, gen, _fut in recs:
                        self._write_record(payload, gen)
                    self._commit()
                    for _, _, fut in recs:
                        if fut is not None and not fut.done():
                            fut.set_result(None)
                except Exception as e:  # pragma: no cover — keep draining
                    log.exception("journal write failed")
                    for _, _, fut in recs:
                        if fut is not None and not fut.done():
                            fut.set_exception(e)
                finally:
                    for _ in recs:
                        self._q.task_done()
            for it in tail:
                try:
                    if it is None:
                        if self._file is not None:
                            self._file.close()
                            self._file = None
                        stop = True
                    else:  # ("snap", (gen, snapshot_bytes))
                        self._write_snapshot(*it[1])
                except Exception:  # pragma: no cover — keep draining  # dynalint: swallow-ok=writer-thread-must-keep-draining
                    log.exception("journal write failed")
                finally:
                    self._q.task_done()

    def _write_record(self, payload: bytes, gen: int) -> None:
        if self._file is None:
            self._file = open(self.journal_path, "ab")
            if os.path.getsize(self.journal_path) == 0:
                _append_record(self._file, {"op": "jhead", "gen": gen})
        self._file.write(_LEN.pack(len(payload)))
        self._file.write(payload)

    def _commit(self) -> None:
        """Flush (and, in durable mode, fsync) the current journal batch."""
        if self._file is not None:
            self._file.flush()
            if self.fsync:
                os.fsync(self._file.fileno())

    def _write_snapshot(self, new_gen: int, snap_bytes: bytes) -> None:
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_LEN.pack(len(snap_bytes)))
            f.write(snap_bytes)
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        if self.fsync:
            # make the rename itself durable (directory entry update)
            try:
                dfd = os.open(os.path.dirname(self.snap_path), os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:  # pragma: no cover — platform-dependent
                pass
        # crash window here: old journal still on disk, but its jhead gen
        # no longer matches the snapshot, so recovery discards it
        if self._file is not None:
            self._file.close()
            self._file = None
        with open(self.journal_path, "wb") as f:
            _append_record(f, {"op": "jhead", "gen": new_gen})
            if self.fsync:
                os.fsync(f.fileno())

    # -- recovery -------------------------------------------------------------

    def recover_into(self, kv: MemoryKVStore, mq: MemoryMessaging) -> int:
        """Rebuild state from snapshot + journal. Returns records replayed."""
        n = 0
        snap_gen = 0
        if os.path.exists(self.snap_path):
            for rec in _read_records(self.snap_path):
                snap_gen = rec.get("gen", 0)
                self.epoch = rec.get("epoch", 0)
                for key, value in rec.get("kv", []):
                    kv._data_restore(key, value)
                for queue, items in rec.get("queues", []):
                    for item in items:
                        mq._queues[queue].put_nowait(item)
        self._gen = snap_gen
        if os.path.exists(self.journal_path):
            records = _read_records(self.journal_path)
            for rec in records:
                if rec["op"] == "jhead":
                    if rec["gen"] != snap_gen:
                        # journal predates the snapshot: compaction crashed
                        # after the snapshot rename but before truncation —
                        # everything here is already in the snapshot
                        log.warning("discarding stale journal (gen %s, "
                                    "snapshot gen %s)", rec["gen"], snap_gen)
                        open(self.journal_path, "wb").close()
                        break
                    continue
                n += 1
                op = rec["op"]
                if op == "put":
                    kv._data_restore(rec["key"], rec["value"])
                elif op == "del":
                    kv._data_drop(rec["key"])
                elif op == "qpush":
                    mq._queues[rec["queue"]].put_nowait(rec["payload"])
                elif op == "qpop":
                    q = mq._queues[rec["queue"]]
                    if not q.empty():
                        q.get_nowait()
                elif op == "epoch":
                    self.epoch = max(self.epoch, rec["epoch"])
        # seed the compaction counter so repeated crash/restart cycles can't
        # grow the journal past compact_every forever (code-review r3)
        self._since_compact = n
        return n

    def compact(self) -> None:
        """Snapshot current persistent state, truncate the journal.

        The state capture (pure in-memory walk + msgpack) happens here, on
        the caller's thread, so it is consistent with the mutation order;
        the file rewrite happens on the writer thread behind any records
        already enqueued."""
        if self._plane is None:
            return
        self._gen += 1
        # one persistent-state builder (snapshot_state) serves both the
        # compaction snapshot and the replication bootstrap — a field
        # added to one cannot silently miss the other (code-review r5)
        snap = {"gen": self._gen, **self._plane.snapshot_state()}
        self._q.put(("snap", (self._gen, msgpack.packb(snap))))
        self._since_compact = 0

    def record_epoch(self, epoch: int) -> None:
        """Persist a fencing-epoch change (standby promotion). The record
        rides the normal append path, so it is replicated to any standbys
        and survives restarts; compaction folds it into the snapshot."""
        self.epoch = epoch
        self.append({"op": "epoch", "epoch": epoch})

    def close(self) -> None:
        """Drain all pending writes and stop the writer thread."""
        with self._close_lock:
            self._closed = True
            self._q.put(None)
        self._writer.join(timeout=30)


async def apply_replicated(plane: "DurablePlane", rec: dict) -> None:
    """Apply one replicated journal record through the plane's DURABLE
    write paths, so a standby journals (and fsyncs) everything it applies
    and can itself be restarted or promoted with no loss (transports/ha).
    """
    op = rec["op"]
    if op == "put":
        await plane.kv.put(rec["key"], rec["value"])
    elif op == "del":
        await plane.kv.delete(rec["key"])
    elif op == "qpush":
        await plane.messaging.queue_push(rec["queue"], rec["payload"])
    elif op == "qpop":
        q = plane.messaging._queues[rec["queue"]]
        if not q.empty():
            q.get_nowait()
            plane.journal.append({"op": "qpop", "queue": rec["queue"]})
    elif op == "epoch":
        # the primary's fencing epoch advanced (it was itself promoted at
        # some point): persist it so this standby rejoins at >= that epoch
        plane.journal.record_epoch(max(plane.journal.epoch, rec["epoch"]))
    # jhead/unknown ops: compaction artifacts of the PRIMARY's journal —
    # meaningless on the standby's own journal, skipped


class DurablePlane(MemoryPlane):
    """MemoryPlane + write-ahead journal; state survives server restarts."""

    def __init__(self, data_dir: str, latency: Optional[LatencyModel] = None,
                 compact_every: int = 10_000, fsync: bool = True):
        self.journal = Journal(data_dir, compact_every, fsync=fsync)
        self.kv = DurableKVStore(self.journal, latency)
        self.messaging = DurableMessaging(self.journal, latency)
        self.journal.attach(self)
        n = self.journal.recover_into(self.kv, self.messaging)
        if n or os.path.exists(self.journal.snap_path):
            log.info("control-plane state recovered (%d journal records)", n)

    def snapshot_state(self) -> dict:
        """Persistent state as one transferable dict (replication bootstrap:
        what a freshly-subscribed standby loads before streaming records).
        Same content as the compaction snapshot: unleased KV + queues."""
        return {
            "epoch": self.journal.epoch,
            "kv": [[k, e.value] for k, e in sorted(self.kv._data.items())
                   if not e.lease_id],
            "queues": [[name, list(q._queue)]
                       for name, q in self.messaging._queues.items()
                       if q.qsize()],
        }

    async def load_snapshot(self, snap: dict) -> None:
        """Replace persistent state with a primary's snapshot (standby
        bootstrap), writing it through the durable paths so the standby's
        own journal captures it."""
        for key in [k for k, e in self.kv._data.items() if not e.lease_id]:
            await self.kv.delete(key)
        for name in list(self.messaging._queues):
            q = self.messaging._queues[name]
            while not q.empty():
                q.get_nowait()
                self.journal.append({"op": "qpop", "queue": name})
        for key, value in snap.get("kv", []):
            await self.kv.put(key, value)
        for name, items in snap.get("queues", []):
            for item in items:
                await self.messaging.queue_push(name, item)

    def close(self) -> None:
        self.journal.close()
