"""Standalone control-plane server: the etcd+NATS replacement.

One asyncio TCP server providing discovery KV (leases, prefix watches), the
request plane (addressed request/reply routed to registered responders),
the event plane (pub/sub), and durable work queues — the roles the reference
outsources to etcd and NATS/JetStream (reference: SURVEY.md §L0,
deploy/docker-compose.yml:16-31). State is held in the same MemoryKVStore/
MemoryMessaging used in-process, so semantics are identical in tests and
deployments.

Run: python -m dynamo_tpu.runtime.transports.server --port 6230
"""
from __future__ import annotations

import argparse
import asyncio
import itertools
import logging
from typing import Dict

from dynamo_tpu.runtime.transports.memory import MemoryPlane
from dynamo_tpu.runtime.transports.wire import read_frame, write_frame

log = logging.getLogger("dynamo_tpu.controlplane")

DEFAULT_PORT = 6230


class _Conn:
    def __init__(self, server: "ControlPlaneServer", reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watch_tasks: Dict[int, asyncio.Task] = {}
        self.sub_tasks: Dict[int, asyncio.Task] = {}
        self.responders: Dict[str, None] = {}
        self.pending_handles: Dict[int, asyncio.Future] = {}
        self.pop_tasks: Dict[int, asyncio.Task] = {}
        self._write_lock = asyncio.Lock()

    async def send(self, msg):
        async with self._write_lock:
            write_frame(self.writer, msg)
            await self.writer.drain()

    async def run(self):
        try:
            while True:
                msg = await read_frame(self.reader)
                asyncio.create_task(self._dispatch(msg))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            await self.cleanup()

    async def cleanup(self):
        for t in list(self.watch_tasks.values()) + list(self.sub_tasks.values()) \
                + list(self.pop_tasks.values()):
            t.cancel()
        for subject in list(self.responders):
            # only deregister if WE are still the registered responder — a
            # reconnected worker may have re-registered the same subject
            if self.server.responders.get(subject) is self:
                del self.server.responders[subject]
        for fut in self.pending_handles.values():
            if not fut.done():
                fut.set_exception(ConnectionError("responder disconnected"))
        self.writer.close()

    async def _dispatch(self, msg):
        op = msg.get("op")
        rid = msg.get("id")
        try:
            handler = getattr(self, f"_op_{op}", None)
            if handler is None:
                raise ValueError(f"unknown op {op!r}")
            result = await handler(msg)
            if rid is not None:
                await self.send({"id": rid, **(result or {})})
        except Exception as e:  # noqa: BLE001 — reported to the peer
            if rid is not None:
                await self.send({"id": rid, "error": f"{type(e).__name__}: {e}"})
            else:
                log.exception("error handling %s", op)

    # -- KV ------------------------------------------------------------------

    async def _op_put(self, m):
        await self.server.plane.kv.put(m["key"], m["value"], m.get("lease", 0))
        return {}

    async def _op_create(self, m):
        ok = await self.server.plane.kv.create(m["key"], m["value"], m.get("lease", 0))
        return {"ok": ok}

    async def _op_get(self, m):
        return {"value": await self.server.plane.kv.get(m["key"])}

    async def _op_get_prefix(self, m):
        entries = await self.server.plane.kv.get_prefix(m["prefix"])
        return {"entries": [[e.key, e.value, e.lease_id] for e in entries]}

    async def _op_delete(self, m):
        await self.server.plane.kv.delete(m["key"])
        return {}

    async def _op_lease_grant(self, m):
        lease = await self.server.plane.kv.grant_lease(m.get("ttl", 10.0))
        self.server.leases[lease.id] = lease
        return {"lease": lease.id}

    async def _op_lease_keepalive(self, m):
        lease = self.server.leases.get(m["lease"])
        if lease is None:
            return {"ok": False}
        lease.keep_alive()
        return {"ok": True}

    async def _op_lease_revoke(self, m):
        lease = self.server.leases.pop(m["lease"], None)
        if lease is not None:
            await lease.revoke()
        return {}

    async def _op_watch(self, m):
        wid = next(self.server.ids)
        snapshot, events = await self.server.plane.kv.watch_prefix(m["prefix"])

        async def pump():
            async for ev in events:
                await self.send({"op": "watch_event", "watch_id": wid,
                                 "kind": ev.kind, "key": ev.key,
                                 "value": ev.value})

        self.watch_tasks[wid] = asyncio.create_task(pump())
        return {"watch_id": wid,
                "entries": [[e.key, e.value, e.lease_id] for e in snapshot]}

    async def _op_unwatch(self, m):
        t = self.watch_tasks.pop(m["watch_id"], None)
        if t:
            t.cancel()
        return {}

    # -- request plane -------------------------------------------------------

    async def _op_serve(self, m):
        subject = m["subject"]
        self.server.responders[subject] = self
        self.responders[subject] = None
        return {}

    async def _op_unserve(self, m):
        subject = m["subject"]
        if self.server.responders.get(subject) is self:
            del self.server.responders[subject]
        self.responders.pop(subject, None)
        return {}

    async def _op_request(self, m):
        responder = self.server.responders.get(m["subject"])
        if responder is None:
            raise ConnectionError(f"no responder on {m['subject']!r}")
        hid = next(self.server.ids)
        fut = asyncio.get_running_loop().create_future()
        responder.pending_handles[hid] = fut
        await responder.send({"op": "handle", "handle_id": hid,
                              "subject": m["subject"], "payload": m["payload"]})
        try:
            payload = await asyncio.wait_for(fut, m.get("timeout", 30.0))
        finally:
            responder.pending_handles.pop(hid, None)
        return {"payload": payload}

    async def _op_reply(self, m):
        fut = self.pending_handles.get(m["handle_id"])
        if fut is not None and not fut.done():
            if m.get("error"):
                fut.set_exception(RuntimeError(m["error"]))
            else:
                fut.set_result(m["payload"])
        return None

    # -- events --------------------------------------------------------------

    async def _op_publish(self, m):
        await self.server.plane.messaging.publish(m["subject"], m["payload"])
        return {}

    async def _op_subscribe(self, m):
        sid = next(self.server.ids)
        gen = await self.server.plane.messaging.subscribe(m["subject"])

        async def pump():
            async for subject, payload in gen:
                await self.send({"op": "event", "sub_id": sid,
                                 "subject": subject, "payload": payload})

        self.sub_tasks[sid] = asyncio.create_task(pump())
        return {"sub_id": sid}

    async def _op_unsubscribe(self, m):
        t = self.sub_tasks.pop(m["sub_id"], None)
        if t:
            t.cancel()
        return {}

    # -- queues --------------------------------------------------------------

    async def _op_queue_push(self, m):
        await self.server.plane.messaging.queue_push(m["queue"], m["payload"])
        return {}

    async def _op_queue_pop(self, m):
        payload = await self.server.plane.messaging.queue_pop(
            m["queue"], m.get("timeout"))
        return {"payload": payload}

    async def _op_queue_depth(self, m):
        return {"depth": await self.server.plane.messaging.queue_depth(m["queue"])}

    async def _op_ping(self, m):
        return {"pong": True}


class ControlPlaneServer:
    def __init__(self, host: str = "127.0.0.1", port: int = DEFAULT_PORT,
                 data_dir: str = None, fsync: bool = True):
        """data_dir enables durability: unleased KV state and work-queue
        contents journal to disk and survive a server restart (the etcd /
        JetStream file-store role; see transports/journal.py). Without it
        the server is pure-memory, as before. fsync=True (default)
        group-commits journal batches to stable storage and acks
        queue_push only after the fsync — machine-crash durable; pass
        False to trade that for lower push latency (flush-only)."""
        self.host, self.port = host, port
        if data_dir:
            from dynamo_tpu.runtime.transports.journal import DurablePlane
            self.plane = DurablePlane(data_dir, fsync=fsync)
        else:
            self.plane = MemoryPlane()
        self.responders: Dict[str, _Conn] = {}
        self.leases: Dict[int, object] = {}
        self.ids = itertools.count(1)
        self._server: asyncio.AbstractServer = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def _on_connect(self, reader, writer):
        await _Conn(self, reader, writer).run()

    async def stop(self):
        if self._server:
            self._server.close()
            await self._server.wait_closed()
        close = getattr(self.plane, "close", None)
        if close:
            close()

    async def serve_forever(self):
        await self.start()
        log.info("control plane listening on %s:%d", self.host, self.port)
        print(f"READY control-plane=:{self.port}", flush=True)
        await asyncio.Event().wait()


def main():
    ap = argparse.ArgumentParser(description="dynamo-tpu control plane server")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=DEFAULT_PORT)
    ap.add_argument("--data-dir", default=None,
                    help="enable durability: journal KV + queues here")
    ap.add_argument("--no-fsync", action="store_true",
                    help="flush-only journal (faster pushes; an OS crash "
                         "may lose acknowledged writes)")
    args = ap.parse_args()
    from dynamo_tpu.utils.logconfig import configure_logging
    configure_logging()
    asyncio.run(ControlPlaneServer(
        args.host, args.port, data_dir=args.data_dir,
        fsync=not args.no_fsync).serve_forever())


if __name__ == "__main__":
    main()
