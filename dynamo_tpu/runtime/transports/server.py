"""Standalone control-plane server: the etcd+NATS replacement.

One asyncio TCP server providing discovery KV (leases, prefix watches), the
request plane (addressed request/reply routed to registered responders),
the event plane (pub/sub), and durable work queues — the roles the reference
outsources to etcd and NATS/JetStream (reference: SURVEY.md §L0,
deploy/docker-compose.yml:16-31). State is held in the same MemoryKVStore/
MemoryMessaging used in-process, so semantics are identical in tests and
deployments.

Run: python -m dynamo_tpu.runtime.transports.server --port 6230
"""
from __future__ import annotations

import argparse
import asyncio
import itertools
import logging
import uuid
from typing import Dict

from dynamo_tpu.runtime.transports.memory import MemoryPlane
from dynamo_tpu.runtime.transports.wire import (
    oneshot_request, read_frame, write_frame,
)

log = logging.getLogger("dynamo_tpu.controlplane")

DEFAULT_PORT = 6230


class _Conn:
    def __init__(self, server: "ControlPlaneServer", reader, writer):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.watch_tasks: Dict[int, asyncio.Task] = {}
        self.sub_tasks: Dict[int, asyncio.Task] = {}
        self.responders: Dict[str, None] = {}
        self.pending_handles: Dict[int, asyncio.Future] = {}
        self.pop_tasks: Dict[int, asyncio.Task] = {}
        self._write_lock = asyncio.Lock()

    async def send(self, msg):
        async with self._write_lock:
            write_frame(self.writer, msg)
            # bounded: one client that stops reading must not wedge every
            # send to its connection behind the write lock (TimeoutError
            # is an OSError — handled like any dead connection)
            await asyncio.wait_for(self.writer.drain(), 30.0)

    async def run(self):
        try:
            while True:
                # dynalint: unbounded-io-ok=idle-client-connections-are-legal
                msg = await read_frame(self.reader)
                asyncio.create_task(self._dispatch(msg))
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            await self.cleanup()

    async def cleanup(self):
        for t in list(self.watch_tasks.values()) + list(self.sub_tasks.values()) \
                + list(self.pop_tasks.values()):
            t.cancel()
        for subject in list(self.responders):
            # only deregister if WE are still the registered responder — a
            # reconnected worker may have re-registered the same subject
            if self.server.responders.get(subject) is self:
                del self.server.responders[subject]
        for fut in self.pending_handles.values():
            if not fut.done():
                fut.set_exception(ConnectionError("responder disconnected"))
        self.writer.close()

    async def _dispatch(self, msg):
        op = msg.get("op")
        rid = msg.get("id")
        try:
            handler = getattr(self, f"_op_{op}", None)
            if handler is None:
                raise ValueError(f"unknown op {op!r}")
            if self.server.role != "primary" and op not in ("role", "ping",
                                                            "fence"):
                # standby/deposed: replicate-only until promoted; clients
                # fail over by probing `role` (tcp.ControlPlaneClient)
                raise ConnectionError(
                    f"{self.server.role} control plane; not serving")
            ep = msg.get("epoch")
            if ep is not None and op not in ("role", "ping"):
                # fencing (VERDICT r4 missing #4): clients echo the epoch
                # of the primary they enrolled with on every op. An op
                # carrying a NEWER epoch proves a later promotion happened
                # somewhere we can't see (partition): step down rather
                # than keep acknowledging divergent writes. An op carrying
                # an OLDER epoch is from a client still enrolled with a
                # deposed primary: refuse it so it re-probes.
                if ep > self.server.epoch:
                    self.server.depose(ep)
                    raise ConnectionError(
                        f"fenced: op epoch {ep} > ours; stepping down")
                if ep < self.server.epoch:
                    raise ConnectionError(
                        f"stale epoch {ep} (primary epoch is "
                        f"{self.server.epoch}); re-probe the control plane")
            result = await handler(msg)
            if rid is not None:
                await self.send({"id": rid, **(result or {})})
        except Exception as e:  # noqa: BLE001 — reported to the peer
            if rid is not None:
                await self.send({"id": rid, "error": f"{type(e).__name__}: {e}"})
            else:
                log.exception("error handling %s", op)

    # -- KV ------------------------------------------------------------------

    async def _op_put(self, m):
        await self.server.plane.kv.put(m["key"], m["value"], m.get("lease", 0))
        return {}

    async def _op_create(self, m):
        ok = await self.server.plane.kv.create(m["key"], m["value"], m.get("lease", 0))
        return {"ok": ok}

    async def _op_get(self, m):
        return {"value": await self.server.plane.kv.get(m["key"])}

    async def _op_get_prefix(self, m):
        entries = await self.server.plane.kv.get_prefix(m["prefix"])
        return {"entries": [[e.key, e.value, e.lease_id] for e in entries]}

    async def _op_delete(self, m):
        await self.server.plane.kv.delete(m["key"])
        return {}

    async def _op_lease_grant(self, m):
        lease = await self.server.plane.kv.grant_lease(m.get("ttl", 10.0))
        self.server.leases[lease.id] = lease
        return {"lease": lease.id}

    async def _op_lease_keepalive(self, m):
        lease = self.server.leases.get(m["lease"])
        if lease is None:
            return {"ok": False}
        lease.keep_alive()
        return {"ok": True}

    async def _op_lease_revoke(self, m):
        lease = self.server.leases.pop(m["lease"], None)
        if lease is not None:
            await lease.revoke()
        return {}

    async def _op_watch(self, m):
        wid = next(self.server.ids)
        snapshot, events = await self.server.plane.kv.watch_prefix(m["prefix"])

        async def pump():
            try:
                async for ev in events:
                    await self.send({"op": "watch_event", "watch_id": wid,
                                     "kind": ev.kind, "key": ev.key,
                                     "value": ev.value})
            finally:
                # deterministic stream teardown (WatchStream no longer
                # relies on generator GC finalization)
                await events.aclose()

        self.watch_tasks[wid] = asyncio.create_task(pump())
        return {"watch_id": wid,
                "entries": [[e.key, e.value, e.lease_id] for e in snapshot]}

    async def _op_unwatch(self, m):
        t = self.watch_tasks.pop(m["watch_id"], None)
        if t:
            t.cancel()
        return {}

    # -- request plane -------------------------------------------------------

    async def _op_serve(self, m):
        subject = m["subject"]
        self.server.responders[subject] = self
        self.responders[subject] = None
        return {}

    async def _op_unserve(self, m):
        subject = m["subject"]
        if self.server.responders.get(subject) is self:
            del self.server.responders[subject]
        self.responders.pop(subject, None)
        return {}

    async def _op_request(self, m):
        responder = self.server.responders.get(m["subject"])
        if responder is None:
            raise ConnectionError(f"no responder on {m['subject']!r}")
        hid = next(self.server.ids)
        fut = asyncio.get_running_loop().create_future()
        responder.pending_handles[hid] = fut
        await responder.send({"op": "handle", "handle_id": hid,
                              "subject": m["subject"], "payload": m["payload"]})
        try:
            payload = await asyncio.wait_for(fut, m.get("timeout", 30.0))
        finally:
            responder.pending_handles.pop(hid, None)
        return {"payload": payload}

    async def _op_reply(self, m):
        fut = self.pending_handles.get(m["handle_id"])
        if fut is not None and not fut.done():
            if m.get("error"):
                fut.set_exception(RuntimeError(m["error"]))
            else:
                fut.set_result(m["payload"])
        return None

    # -- events --------------------------------------------------------------

    async def _op_publish(self, m):
        await self.server.plane.messaging.publish(m["subject"], m["payload"])
        return {}

    async def _op_subscribe(self, m):
        sid = next(self.server.ids)
        gen = await self.server.plane.messaging.subscribe(m["subject"])

        async def pump():
            async for subject, payload in gen:
                await self.send({"op": "event", "sub_id": sid,
                                 "subject": subject, "payload": payload})

        self.sub_tasks[sid] = asyncio.create_task(pump())
        return {"sub_id": sid}

    async def _op_unsubscribe(self, m):
        t = self.sub_tasks.pop(m["sub_id"], None)
        if t:
            t.cancel()
        return {}

    # -- queues --------------------------------------------------------------

    async def _op_queue_push(self, m):
        await self.server.plane.messaging.queue_push(m["queue"], m["payload"])
        return {}

    async def _op_queue_pop(self, m):
        payload = await self.server.plane.messaging.queue_pop(
            m["queue"], timeout=m.get("timeout"))
        return {"payload": payload}

    async def _op_queue_pop_leased(self, m):
        got = await self.server.plane.messaging.queue_pop_leased(
            m["queue"], timeout=m.get("timeout"),
            lease_s=m.get("lease_s") or 30.0)
        if got is None:
            return {"payload": None, "token": None}
        return {"payload": got[0], "token": got[1]}

    async def _op_queue_ack(self, m):
        await self.server.plane.messaging.queue_ack(m["queue"], m["token"])
        return {}

    async def _op_queue_touch(self, m):
        alive = await self.server.plane.messaging.queue_touch(
            m["queue"], m["token"], lease_s=m.get("lease_s") or 30.0)
        return {"alive": bool(alive)}

    async def _op_queue_depth(self, m):
        return {"depth": await self.server.plane.messaging.queue_depth(m["queue"])}

    async def _op_ping(self, m):
        return {"pong": True}

    # -- HA replication (transports HA role; VERDICT r3 missing #3) ----------

    async def _op_role(self, m):
        return {"role": self.server.role, "synced": self.server.synced,
                "epoch": self.server.epoch}

    async def _op_fence(self, m):
        """A promoted member announces its epoch; a PRIMARY carrying an
        older epoch steps down — and, when the fence names the winner's
        port, REJOINS as its hot standby (self-healing pair: after a
        partition heals or a stale member restarts, replication re-forms
        without operator action). Carried in `fence_epoch` (not `epoch`)
        so it bypasses the client-echo gate — fencing must reach a member
        regardless of its role. A standby only tracks the newer epoch:
        deposing it would silently kill its _replicate loop and leave the
        pair with no replication at all (code-review r5)."""
        ep = m["fence_epoch"]
        rejoin = None
        if m.get("port"):
            # the winner as seen from THIS member: the fencing
            # connection's source host + its advertised port
            peer = self.writer.get_extra_info("peername")
            if peer:
                rejoin = (peer[0], int(m["port"]))
        # equal-epoch tie-break on the per-promotion id: covers a reborn
        # member whose journal carries the same epoch the winner holds.
        # (It does NOT solve two sibling standbys promoting to the same
        # epoch — they only fence their old primary, never each other;
        # see the class docstring's multi-standby caveat.)
        loses_tie = (ep == self.server.epoch
                     and m.get("promo_id", "") > self.server.promo_id)
        if ep > self.server.epoch or loses_tie:
            if self.server.role == "primary":
                self.server.depose(ep, rejoin=rejoin)
            else:
                self.server.epoch = ep
        elif (ep >= self.server.epoch and rejoin
                and self.server.role == "deposed"):
            # deposed earlier by a client op (which carries no address);
            # the winner's fence now names one — late self-heal
            self.server.depose(ep, rejoin=rejoin)
        return {"role": self.server.role, "epoch": self.server.epoch}

    async def _op_repl_subscribe(self, m):
        """Standby bootstrap: a consistent snapshot of persistent state,
        then every journal record streamed in append order. Snapshot
        capture and subscriber registration happen in one event-loop
        step (no awaits), so no record can fall in the gap."""
        plane = self.server.plane
        if not hasattr(plane, "snapshot_state"):
            raise ValueError("replication requires a durable primary "
                             "(start it with --data-dir)")
        if self.server.role != "primary":
            raise ValueError("cannot replicate from a standby")
        sid = next(self.server.ids)
        # bounded (ADVICE r4): a standby that stops draining must not grow
        # primary memory without limit — on overflow the subscriber is
        # evicted and its connection closed, so it re-bootstraps from a
        # fresh snapshot when it recovers
        q: asyncio.Queue = asyncio.Queue(maxsize=self.server.repl_backlog)
        snap = plane.snapshot_state()
        self.server.repl_subs[sid] = (q, self)

        async def pump():
            try:
                while True:
                    rec = await q.get()
                    await self.send({"op": "repl_rec", "rec": rec})
            except OSError:
                pass  # evicted mid-send or link dropped; the subscriber
                # re-bootstraps — not an error worth an unretrieved-task log
            finally:
                self.server.repl_subs.pop(sid, None)

        self.sub_tasks[sid] = asyncio.create_task(pump())
        return {"snapshot": snap}


class ControlPlaneServer:
    def __init__(self, host: str = "127.0.0.1", port: int = DEFAULT_PORT,
                 data_dir: str = None, fsync: bool = True,
                 standby_of: tuple = None):
        """data_dir enables durability: unleased KV state and work-queue
        contents journal to disk and survive a server restart (the etcd /
        JetStream file-store role; see transports/journal.py). Without it
        the server is pure-memory, as before. fsync=True (default)
        group-commits journal batches to stable storage and acks
        queue_push only after the fsync — machine-crash durable; pass
        False to trade that for lower push latency (flush-only).

        standby_of=(host, port) runs this server as a HOT STANDBY of a
        durable primary (VERDICT r3 missing #3 — the reference inherits
        HA from raft-replicated etcd / clustered JetStream): it
        bootstraps from the primary's snapshot, applies its journal
        record stream continuously (journaling everything locally, so
        the standby is itself restartable), refuses client ops, and
        PROMOTES itself to primary the moment the replication link
        drops after a successful sync. Clients list both addresses
        (tcp.ControlPlaneClient probes roles and follows the primary).
        Leases and watches are ephemeral by design (etcd semantics) —
        workers re-register against the promoted standby.

        FENCED promotion (VERDICT r4 #4): every promotion bumps a
        monotonic epoch, persisted in the journal and returned by
        `role`. Clients echo their enrolled epoch on every op; a member
        refuses ops from an older epoch, and STEPS DOWN the moment any
        op proves a newer epoch exists. Clients pick the highest-epoch
        primary among all members they can reach, so a partition
        between the pair cannot split epoch-aware clients between two
        primaries: the first post-promotion contact deposes the old
        primary. SELF-HEALING: the winner's fence message names its
        address, so a deposed durable member rejoins as the winner's
        hot standby automatically (snapshot bootstrap discards its
        divergent stale tail) — after a partition heals or a stale
        member restarts, replication redundancy re-forms with no
        operator action. An equal-epoch fence tie-breaks on a
        per-promotion id (covers a reborn member whose journal holds the
        winner's epoch). Known limitation: TWO standbys of one primary
        that promote concurrently reach the same epoch and never fence
        each other — run the pair topology (one standby), not a fan-out,
        unless dual-primary-at-equal-epoch is acceptable.
        What this is NOT: raft. A client that can reach ONLY the old
        primary keeps writing at the old epoch until any newer-epoch
        traffic arrives; the reference inherits quorum from etcd
        (lib/runtime/src/transports/etcd.rs:90-120) and gives up
        minority-side availability instead. The fence guarantees
        acknowledged writes never interleave across epochs on one
        member and that divergence is detectable (every write is
        epoch-tagged) — not that the minority side goes read-only
        instantly."""
        self.host, self.port = host, port
        if data_dir:
            from dynamo_tpu.runtime.transports.journal import DurablePlane
            self.plane = DurablePlane(data_dir, fsync=fsync)
        else:
            self.plane = MemoryPlane()
        self.responders: Dict[str, _Conn] = {}
        self.leases: Dict[int, object] = {}
        self.ids = itertools.count(1)
        self._server: asyncio.AbstractServer = None
        self.standby_of = standby_of
        self.role = "standby" if standby_of else "primary"
        self.synced = False
        self.repl_subs: Dict[int, tuple] = {}  # sid -> (queue, conn)
        self.repl_backlog = 10_000
        self._repl_task: asyncio.Task = None
        self._fence_task: asyncio.Task = None
        self._conns: set = set()
        journal = getattr(self.plane, "journal", None)
        if journal is not None:
            journal.on_record = self._fanout_record
        # fencing epoch: recovered from the journal if durable (a restarted
        # member rejoins at the epoch it held); a fresh primary starts at 1
        self.epoch = max(1, journal.epoch) if journal is not None else 1
        if journal is not None:
            journal.epoch = self.epoch
        # per-promotion id, the equal-epoch fence tie-break (two standbys
        # of one primary can both promote to the same epoch)
        self.promo_id = ""

    def depose(self, newer_epoch: int, rejoin: tuple = None) -> None:
        """Step down: a peer proved a newer promotion epoch exists (we
        are the stale side of a partition). Refuse all further ops so our
        clients fail over to the real primary; remember the newer epoch so
        `role` reports it. Deliberately NOT journaled: a deposed member
        restarting comes back as primary at its OLD epoch and is re-fenced
        by the first epoch-tagged op — journaling the newer epoch would
        instead resurrect it as a second primary AT the new epoch.

        With `rejoin` (the winner's address, from its fence message) a
        DURABLE member doesn't stay a dead end: it re-enters the pair as
        the winner's hot standby — bootstrapping from its snapshot (which
        discards our divergent stale-epoch tail; that divergence is the
        documented non-raft trade) and streaming its journal — so
        replication redundancy self-heals after a partition or a stale
        restart, with no operator action."""
        if self.role == "primary":
            log.warning("DEPOSED: op carried epoch %d >= ours %d; refusing "
                        "all ops on :%d", newer_epoch, self.epoch, self.port)
        self.role = "deposed"
        self.epoch = max(self.epoch, newer_epoch)
        # our own fencing loop (from a past promotion) must die with the
        # primacy it defended: left running it would keep fencing with
        # OUR stale promo_id at the now-shared epoch and could depose the
        # healthy winner — two standbys of each other, no primary at all
        # (code-review r5)
        if self._fence_task is not None:
            self._fence_task.cancel()
            self._fence_task = None
        if rejoin and hasattr(self.plane, "snapshot_state"):
            log.warning("rejoining as hot standby of %s:%d", *rejoin)
            self.standby_of = rejoin
            self.synced = False
            self.role = "standby"
            if self._repl_task is None or self._repl_task.done():
                self._repl_task = asyncio.create_task(self._replicate())

    def _fanout_record(self, rec: dict) -> None:
        for sid, (q, conn) in list(self.repl_subs.items()):
            try:
                q.put_nowait(rec)
            except asyncio.QueueFull:
                log.warning("replication subscriber %d fell %d records "
                            "behind; evicting (it will re-bootstrap from "
                            "a snapshot)", sid, self.repl_backlog)
                self.repl_subs.pop(sid, None)
                # the standby distinguishes this eviction from primary
                # death by probing our role before promoting (_replicate):
                # we are alive and still primary, so it re-bootstraps
                conn.writer.close()

    async def start(self):
        self._server = await asyncio.start_server(
            self._on_connect, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        if self.standby_of is not None:
            if not hasattr(self.plane, "snapshot_state"):
                raise ValueError("a standby needs --data-dir (it journals "
                                 "the replicated state locally)")
            self._repl_task = asyncio.create_task(self._replicate())
        return self

    async def _replicate(self):
        """Standby loop: sync from the primary until the link dies, then
        promote. Connection refused BEFORE any successful sync keeps
        retrying (the primary may simply not be up yet)."""
        from dynamo_tpu.runtime.transports.journal import apply_replicated
        host, port = self.standby_of
        while self.role == "standby":
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port), 5.0)
            except (OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.5)
                continue
            try:
                write_frame(writer, {"op": "repl_subscribe", "id": 1})
                # one tiny frame: cannot fill the peer's recv window, but
                # bound it anyway so a wedged primary can't pin the standby
                await asyncio.wait_for(writer.drain(), 30.0)
                while True:
                    # dynalint: unbounded-io-ok=replication-stream-is-push —
                    # the primary sends journal records as writes happen;
                    # link death surfaces as EOF and the loop re-dials
                    m = await read_frame(reader)
                    if m.get("id") == 1:
                        if m.get("error"):
                            raise ConnectionError(m["error"])
                        snap_ep = m["snapshot"].get("epoch", 1)
                        my_ep = self.plane.journal.epoch
                        if snap_ep < my_ep:
                            # the "primary" we were pointed at is STALE:
                            # our own journal carries a higher promotion
                            # epoch (we were promoted in a past life and
                            # acknowledged writes at it). Syncing would
                            # destroy that acknowledged history — refuse,
                            # resume primacy at our epoch, and fence the
                            # stale peer (code-review r5; this is also
                            # what re-arms fencing after a restart).
                            log.error(
                                "peer %s:%d offers snapshot epoch %d "
                                "below our journaled epoch %d; refusing "
                                "to sync — resuming primacy and fencing "
                                "it", host, port, snap_ep, my_ep)
                            self.epoch = my_ep
                            self.promo_id = uuid.uuid4().hex
                            self.role = "primary"
                            self._arm_fence(host, port)
                            print(f"PROMOTED control-plane=:{self.port}",
                                  flush=True)
                            return
                        await self.plane.load_snapshot(m["snapshot"])
                        # track the primary's fencing epoch so promotion
                        # can bump PAST it (not to some stale local value)
                        self.epoch = max(self.epoch, snap_ep)
                        self.synced = True
                        log.info("standby synced from %s:%d (epoch %d)",
                                 host, port, self.epoch)
                    elif m.get("op") == "repl_rec":
                        await apply_replicated(self.plane, m["rec"])
                        if m["rec"].get("op") == "epoch":
                            self.epoch = max(self.epoch, m["rec"]["epoch"])
            except (asyncio.IncompleteReadError, ConnectionError, OSError):
                pass
            finally:
                writer.close()
            if self.synced and await self._primary_alive(host, port):
                # link lost but the primary still answers as primary: we
                # were EVICTED (fell behind the bounded replication queue)
                # or hit a transient close — promoting here would fence a
                # healthy primary off a replica missing records. Re-
                # bootstrap from a fresh snapshot instead (code-review r5).
                log.warning("replication link lost but primary %s:%d is "
                            "alive; re-bootstrapping instead of promoting",
                            host, port)
                self.synced = False
                await asyncio.sleep(0.5)
                continue
            if self.synced:
                self.epoch += 1
                self.plane.journal.record_epoch(self.epoch)
                self.promo_id = uuid.uuid4().hex
                self.role = "primary"
                log.warning("replication link to %s:%d lost; PROMOTED to "
                            "primary on :%d at epoch %d", host, port,
                            self.port, self.epoch)
                print(f"PROMOTED control-plane=:{self.port}", flush=True)
                # keep trying to fence the old primary: if the link loss
                # was a partition (old primary alive) or it later restarts
                # from its data dir, it must learn the newer epoch and
                # step down instead of serving old-epoch clients forever
                self._arm_fence(host, port)
                return
            await asyncio.sleep(0.5)

    async def _primary_alive(self, host, port) -> bool:
        """One role probe with a hard timeout: does the peer still answer
        as a primary? Used by the standby to tell eviction/transient
        closes (primary alive -> re-bootstrap) from primary death or a
        partition (unreachable -> promote)."""
        try:
            m = await oneshot_request(host, port, {"op": "role"}, 3.0)
            return m.get("role") == "primary"
        except (OSError, ConnectionError, asyncio.IncompleteReadError,
                asyncio.TimeoutError):
            return False

    def _arm_fence(self, host, port):
        """(Re)start the fencing loop toward the peer we superseded; any
        loop from an earlier promotion is cancelled first so exactly one
        fence task defends the current primacy."""
        if self._fence_task is not None:
            self._fence_task.cancel()
        self._fence_task = asyncio.create_task(self._fence_peer(host, port))

    async def _fence_peer(self, host, port):
        # runs for the promoted member's whole life, not just until the
        # first successful fence: a deposed peer that RESTARTS from its
        # data dir comes back as primary at its old epoch (deposition is
        # deliberately not journaled — see depose()) and must be re-fenced
        fenced = False
        while True:
            try:
                m = await oneshot_request(
                    host, port,
                    {"op": "fence", "fence_epoch": self.epoch,
                     "port": self.port, "promo_id": self.promo_id},
                    5.0)
                now_fenced = m.get("role") != "primary"
                if now_fenced and not fenced:
                    log.info("old primary %s:%d fenced (role=%s)",
                             host, port, m.get("role"))
                fenced = now_fenced
            except (OSError, ConnectionError, asyncio.IncompleteReadError,
                    asyncio.TimeoutError):
                fenced = False  # dead or still partitioned; keep trying
            await asyncio.sleep(2.0)

    async def _on_connect(self, reader, writer):
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            await conn.run()
        finally:
            self._conns.discard(conn)

    async def stop(self):
        if self._repl_task:
            self._repl_task.cancel()
        if self._fence_task:
            self._fence_task.cancel()
        if self._server:
            self._server.close()
            # 3.12 wait_closed() waits for every open connection; a hot
            # standby holds its replication stream open indefinitely, so
            # close them actively (their handlers then run cleanup())
            for conn in list(self._conns):
                conn.writer.close()
            await self._server.wait_closed()
        close = getattr(self.plane, "close", None)
        if close:
            close()

    async def serve_forever(self):
        await self.start()
        log.info("control plane listening on %s:%d", self.host, self.port)
        print(f"READY control-plane=:{self.port}", flush=True)
        await asyncio.Event().wait()


def main():
    # layered settings (utils/settings.py, figment-style): struct defaults
    # <- DYN_CONFIG file <- DYN_* env; CLI flags beat all of them. e.g.
    # DYN_CONTROL_PLANE__PORT=7000 or a TOML [control_plane] section.
    from dynamo_tpu.utils.settings import load_settings
    s = load_settings({"control_plane": {
        "host": "0.0.0.0", "port": DEFAULT_PORT, "data_dir": None,
        "fsync": True, "standby_of": None}}).control_plane
    ap = argparse.ArgumentParser(description="dynamo-tpu control plane server")
    ap.add_argument("--host", default=s.host)
    ap.add_argument("--port", type=int, default=s.port)
    ap.add_argument("--data-dir", default=s.data_dir,
                    help="enable durability: journal KV + queues here")
    ap.add_argument("--no-fsync", action="store_true", default=not s.fsync,
                    help="flush-only journal (faster pushes; an OS crash "
                         "may lose acknowledged writes)")
    ap.add_argument("--standby-of", default=s.standby_of, metavar="HOST:PORT",
                    help="run as a hot standby replicating this primary; "
                         "promotes itself when the link drops (needs "
                         "--data-dir)")
    args = ap.parse_args()
    from dynamo_tpu.utils.logconfig import configure_logging
    configure_logging()
    standby = None
    if args.standby_of:
        h, _, p = args.standby_of.rpartition(":")
        standby = (h or "127.0.0.1", int(p))
    asyncio.run(ControlPlaneServer(
        args.host, args.port, data_dir=args.data_dir,
        fsync=not args.no_fsync, standby_of=standby).serve_forever())


if __name__ == "__main__":
    main()
