"""Standalone metrics exporter: scrapes worker load metrics into Prometheus.

Role-equivalent of the reference's `components/metrics` binary (reference:
components/metrics/src/lib.rs:96-616 + main.rs): a separate process that
watches a component's live instances, scrapes each worker's
ForwardPassMetrics through the stats plane, folds them into
ProcessedEndpoints, and serves Prometheus gauges (`llm_kv_blocks_*`,
`llm_requests_*`, load avg/std) on GET /metrics. It also subscribes to the
router's `kv-hit-rate` events (reference: KVHitRateEvent handling,
lib.rs:433-512).

Run: python -m dynamo_tpu.observability.exporter \
        --coordinator 127.0.0.1:6230 --namespace ns --component worker \
        --endpoint generate --port 9091
"""
from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Optional

from dynamo_tpu.kv_router.publisher import (
    KV_HIT_RATE_SUBJECT, KvMetricsAggregator,
)
from dynamo_tpu.observability.metrics import MetricsRegistry

log = logging.getLogger("dynamo_tpu.metrics_exporter")

PREFIX = "llm"


class MetricsExporter:
    """Aggregator + Prometheus endpoint for one component's worker fleet."""

    def __init__(self, runtime, namespace: str, component: str,
                 endpoint: str = "generate", port: int = 9091,
                 scrape_interval_s: float = 0.5):
        self.runtime = runtime
        self.namespace, self.component_name = namespace, component
        self.endpoint_name = endpoint
        self.port = port
        self._interval_s = scrape_interval_s
        self.registry = MetricsRegistry()
        labels = ("worker",)
        r = self.registry
        self.g_active_slots = r.gauge(
            f"{PREFIX}_requests_active_slots",
            "Decode slots currently generating", labels)
        self.g_total_slots = r.gauge(
            f"{PREFIX}_requests_total_slots", "Decode slot capacity", labels)
        self.g_kv_active = r.gauge(
            f"{PREFIX}_kv_blocks_active", "KV pages in use", labels)
        self.g_kv_total = r.gauge(
            f"{PREFIX}_kv_blocks_total", "KV page capacity", labels)
        self.g_waiting = r.gauge(
            f"{PREFIX}_requests_waiting", "Requests queued for prefill",
            labels)
        self.g_usage = r.gauge(
            f"{PREFIX}_kv_cache_usage_percent",
            "KV cache usage fraction [0,1]", labels)
        self.g_hit_rate = r.gauge(
            f"{PREFIX}_prefix_cache_hit_rate",
            "Worker-reported prefix cache hit rate", labels)
        self.g_window_steps = r.gauge(
            f"{PREFIX}_window_slot_steps",
            "Cumulative decode-window (step, slot) pairs run", labels)
        self.g_window_wasted = r.gauge(
            f"{PREFIX}_window_wasted_steps",
            "Of those, steps after the slot's request finished", labels)
        self.g_spec_proposed = r.gauge(
            f"{PREFIX}_spec_proposed_tokens",
            "Cumulative speculative draft tokens verified", labels)
        self.g_spec_accepted = r.gauge(
            f"{PREFIX}_spec_accepted_tokens",
            "Of those, drafts accepted (free decode tokens)", labels)
        # overlapped decode pipeline occupancy (engine pipelined loop):
        # overlapped/pipelined is the live host-overlap rate; fallbacks
        # count reconciliation discards; plan_uploads staying flat while
        # windows climbs is the zero-upload steady-state invariant
        self.g_pipe = {
            name: r.gauge(f"{PREFIX}_decode_{name}", help_, labels)
            for name, help_ in (
                ("windows", "Decode windows dispatched"),
                ("pipeline_windows",
                 "Of those, committed via the overlapped pipeline"),
                ("pipeline_overlapped",
                 "Commits that ran while a follow-up window executed"),
                ("pipeline_fallbacks",
                 "In-flight windows discarded on membership change"),
                ("host_syncs", "Blocking output fetches in decode"),
                ("plan_uploads", "Windows that staged fresh host arrays"),
                ("mixed_steps",
                 "Fused prefill+decode device steps run"),
                ("stall_steps",
                 "Steps where running streams emitted nothing (decode "
                 "stalled by a prefill-only step)"),
            )}
        # KV representation gauges (ops/kv_quant.py): page HBM footprint,
        # quant mode bit width (0 = unquantized, 8 = int8 pages), and
        # transfer volume in the wire representation — bytes_per_fetch is
        # the disagg handoff cost the kv_quant capacity bench halves
        self.g_kv_repr = {
            name: r.gauge(f"{PREFIX}_kv_{name}", help_, labels)
            for name, help_ in (
                ("page_bytes", "HBM bytes per KV page (k+v+scales)"),
                ("quant_mode",
                 "KV page quant bit width (0 = unquantized, 8 = int8)"),
                ("transfer_bytes",
                 "Cumulative KV transfer payload bytes (wire "
                 "representation: quantized on kv_quant engines)"),
                ("transfer_fetches", "Cumulative KV transfer fetches"),
                ("transfer_bytes_per_fetch",
                 "Mean KV transfer payload bytes per fetch"),
                # chunk-committed streaming (disagg/remote_transfer.py)
                ("transfer_resumes",
                 "KV transfers resumed from a committed frontier "
                 "(link failure or replacement sender)"),
                ("transfer_salvaged_pages",
                 "Committed-prefix pages re-used by decode-side salvage "
                 "instead of local re-prefill"),
                ("transfer_stale_chunks",
                 "Transfer chunks rejected by the alloc-epoch fence "
                 "(stale sender after realloc)"),
                ("transfer_link_timeouts",
                 "Per-IO socket timeouts treated as transfer link death"),
            )}
        # per-step ledger figures (observability/ledger.py via
        # EngineMetrics): committed steps, recompile events, EWMA tok/s,
        # MFU estimate, padding-waste fraction, offload tier occupancy
        self.g_engine = {
            name: r.gauge(f"{PREFIX}_engine_{name}", help_, labels)
            for name, help_ in (
                ("steps", "Device steps committed (ledger samples)"),
                ("recompiles",
                 "New (program, bucket) keys dispatched (XLA compiles)"),
                ("tok_s", "EWMA instantaneous useful tokens/s"),
                ("mfu", "Model FLOPs utilization estimate (0 = no peak "
                        "configured)"),
                ("pad_frac",
                 "Cumulative bucket-ladder padding-waste fraction"),
                ("host_pages_used", "Host-DRAM KV tier pages in use"),
                ("host_pages_total", "Host-DRAM KV tier page capacity"),
                ("disk_pages_used", "Disk KV tier pages in use"),
                ("disk_pages_total", "Disk KV tier page capacity"),
            )}
        # tiered-KV streaming decode (engine/streaming.py via
        # EngineMetrics): contexts beyond the HBM page budget — prefetch
        # hit/late is the double-buffer health signal (hit >> late on a
        # well-provisioned tier), quarantines count verify-on-fetch rot
        self.g_kv_stream = {
            name: r.gauge(f"{PREFIX}_kv_stream_{name}", help_, labels)
            for name, help_ in (
                ("steps", "Streamed decode/prefill steps run"),
                ("prefetch_hit",
                 "Window-pool segment consumes served by a completed "
                 "double-buffer prefetch"),
                ("prefetch_late",
                 "Window-pool segment consumes that staged synchronously "
                 "(prefetch missed the compute window)"),
                ("pages_spilled",
                 "Resident KV pages spilled to the offload hierarchy by "
                 "the attention-mass EWMA policy"),
                ("pages_quarantined",
                 "Cold pages that failed the verify-on-fetch checksum "
                 "gate (each recomputed from its token span)"),
                ("stall_steps",
                 "Streamed steps that consumed at least one late "
                 "segment"),
            )}
        self.g_load_avg = r.gauge(
            f"{PREFIX}_load_avg", "Mean active KV blocks across workers")
        self.g_load_std = r.gauge(
            f"{PREFIX}_load_std", "Stddev of active KV blocks across workers")
        self.g_workers = r.gauge(
            f"{PREFIX}_workers", "Live worker instances")
        self.g_router_hit = r.gauge(
            f"{PREFIX}_router_kv_hit_rate",
            "ISL-weighted router overlap rate (kv-hit-rate events)")
        # reliability layer counters (frontend/reliability.py), published
        # as snapshots on "{ns}.{component}.reliability" by each frontend;
        # gauges mirror the source's counters, labeled by publisher
        from dynamo_tpu.frontend.reliability import ReliabilityMetrics
        self.g_reliability = {
            name: r.gauge(f"{PREFIX}_reliability_{name}",
                          f"reliability layer: cumulative {name} "
                          "at the publishing frontend", ("source",))
            for name in ReliabilityMetrics.FIELDS}
        # control-plane health of THIS exporter process (its own Client
        # watch + aggregator — the same watch fan-out every frontend
        # runs, so its lag/resync counters are a representative canary);
        # refreshed from runtime/cpstats.py CP_STATS at render time
        from dynamo_tpu.runtime.cpstats import ControlPlaneStats
        self.g_cp = {
            name: r.gauge(f"{PREFIX}_cp_{name}",
                          f"control plane: {name.replace('_', ' ')}")
            for name in ControlPlaneStats.FIELDS}
        # transfer-aware router scoring counters (kv_router/stats.py),
        # same render-time refresh — when the exporter process hosts a
        # router these are its scoring health, otherwise they render 0
        from dynamo_tpu.kv_router.stats import RouterScoringStats
        self.g_router = {
            name: r.gauge(f"{PREFIX}_router_{name}",
                          f"router scoring: {name.replace('_', ' ')}")
            for name in RouterScoringStats.FIELDS}
        # closed-loop autoscaler counters (runtime/autoscaler.py), same
        # render-time refresh — when this process hosts the controller
        # these are its decision health, otherwise they render 0
        from dynamo_tpu.runtime.autoscaler import AutoscalerStats
        self.g_autoscaler = {
            name: r.gauge(f"{PREFIX}_autoscaler_{name}",
                          f"fleet autoscaler: {name.replace('_', ' ')}")
            for name in AutoscalerStats.FIELDS}
        # cluster-wide shared KV pool counters (engine/kv_pool.py), same
        # render-time refresh — when this process hosts the pool (or a
        # publishing/fetching engine) these are its reuse health
        from dynamo_tpu.engine.kv_pool import KvPoolStats
        self.g_kv_pool = {
            name: r.gauge(f"{PREFIX}_kv_pool_{name}",
                          f"shared kv pool: {name.replace('_', ' ')}")
            for name in KvPoolStats.FIELDS}
        # cross-host pool service (engine/pool_service.py): remote
        # fetch/failover/quorum health + placement-ring membership and
        # rebalance progress, same render-time refresh
        from dynamo_tpu.engine.pool_service import (
            PoolRingStats, RemotePoolStats,
        )
        self.g_kv_pool_remote = {
            name: r.gauge(f"{PREFIX}_kv_pool_remote_{name}",
                          f"cross-host kv pool: {name.replace('_', ' ')}")
            for name in RemotePoolStats.FIELDS}
        self.g_pool_ring = {
            name: r.gauge(f"{PREFIX}_pool_ring_{name}",
                          f"pool placement ring: {name.replace('_', ' ')}")
            for name in PoolRingStats.FIELDS}
        # fail-slow plane (runtime/health.py): gray-failure detection
        # counters (HEALTH_STATS) + hedged-dispatch outcomes
        # (HEDGE_STATS), same render-time refresh — live when this
        # process hosts a reliability layer or scorer, 0 otherwise
        from dynamo_tpu.runtime.health import HealthStats, HedgeStats
        self.g_health = {
            name: r.gauge(f"{PREFIX}_health_{name}",
                          f"fail-slow detection: {name.replace('_', ' ')}")
            for name in HealthStats.FIELDS}
        self.g_hedge = {
            name: r.gauge(f"{PREFIX}_hedge_{name}",
                          f"hedged dispatch: {name.replace('_', ' ')}")
            for name in HedgeStats.FIELDS}
        self.g_hedge_by_class = r.gauge(
            f"{PREFIX}_hedge_fired_by_class",
            "hedged dispatch: hedges fired per QoS class", ("qos",))
        self._client = None
        self._aggregator: Optional[KvMetricsAggregator] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._sub_task: Optional[asyncio.Task] = None
        # cumulative KVHitRateEvent totals (reference lib.rs:433-512)
        self._hit_isl = 0
        self._hit_overlap = 0

    async def start(self) -> "MetricsExporter":
        ep = self.runtime.namespace(self.namespace).component(
            self.component_name).endpoint(self.endpoint_name)
        self._client = ep.client()
        await self._client.start()
        # watch-event series eviction: delete/draining events drop the
        # instance's label series immediately (the scrape-driven
        # `removed` pass below stays as the backstop)
        self._client.add_listener(self._on_instance)
        self._aggregator = KvMetricsAggregator(
            self._client, interval_s=self._interval_s)
        self._aggregator.on_update(self._on_update)
        await self._aggregator.start()
        # the router publishes kv-hit-rate on ITS component subject
        # ({ns}.{router_component}.kv-hit-rate); subscribe with a namespace
        # wildcard and filter, so the exporter needn't know the router name
        raw = await self.runtime.messaging.subscribe(f"{self.namespace}.>")
        self._sub_task = asyncio.create_task(self._consume_hit_rate(raw))
        self._server = await asyncio.start_server(
            self._serve_http, "0.0.0.0", self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._aggregator:
            await self._aggregator.stop()
        if self._sub_task:
            self._sub_task.cancel()
        if self._client is not None:
            await self._client.stop()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    # -- aggregation ----------------------------------------------------------

    def _worker_gauges(self):
        """Every per-instance gauge family (the ('worker',) label set)."""
        return (self.g_active_slots, self.g_total_slots,
                self.g_kv_active, self.g_kv_total, self.g_waiting,
                self.g_usage, self.g_hit_rate, self.g_window_steps,
                self.g_window_wasted, self.g_spec_proposed,
                self.g_spec_accepted, *self.g_pipe.values(),
                *self.g_kv_repr.values(), *self.g_engine.values(),
                *self.g_kv_stream.values())

    def _evict_worker_series(self, worker_id: str) -> None:
        for g in self._worker_gauges():
            g.remove(worker_id)

    def _on_instance(self, kind: str, worker_id: str, info) -> None:
        """Watch-event label-series eviction (the kv_router's
        `on_instance` pattern): a departed or draining worker's
        per-instance series drop the moment its delete/draining event
        is APPLIED — not a scrape interval later. Without this, a
        scrape loop that stalls (or a fleet that churns faster than it
        scrapes) leaks one series set per dead instance and the
        exporter's /metrics grows without bound (rolling-restart churn
        test in tests/test_metrics_exporter.py)."""
        from dynamo_tpu.runtime.component import STATUS_DRAINING
        if kind == "delete" or (
                info is not None and info.get("status") == STATUS_DRAINING):
            self._evict_worker_series(worker_id)

    def _on_update(self, endpoints, removed) -> None:
        for worker_id in removed:
            self._evict_worker_series(worker_id)
        for worker_id, m in endpoints.workers.items():
            self.g_active_slots.set(worker_id, value=m.request_active_slots)
            self.g_total_slots.set(worker_id, value=m.request_total_slots)
            self.g_kv_active.set(worker_id, value=m.kv_active_blocks)
            self.g_kv_total.set(worker_id, value=m.kv_total_blocks)
            self.g_waiting.set(worker_id, value=m.num_requests_waiting)
            self.g_usage.set(worker_id, value=m.gpu_cache_usage_perc)
            self.g_hit_rate.set(worker_id,
                                value=m.gpu_prefix_cache_hit_rate)
            self.g_window_steps.set(worker_id, value=m.window_slot_steps)
            self.g_window_wasted.set(worker_id,
                                     value=m.window_wasted_steps)
            self.g_spec_proposed.set(worker_id,
                                     value=m.spec_proposed_tokens)
            self.g_spec_accepted.set(worker_id,
                                     value=m.spec_accepted_tokens)
            self.g_pipe["windows"].set(worker_id, value=m.decode_windows)
            self.g_pipe["pipeline_windows"].set(
                worker_id, value=m.pipeline_windows)
            self.g_pipe["pipeline_overlapped"].set(
                worker_id, value=m.pipeline_overlapped)
            self.g_pipe["pipeline_fallbacks"].set(
                worker_id, value=m.pipeline_fallbacks)
            self.g_pipe["host_syncs"].set(
                worker_id, value=m.decode_host_syncs)
            self.g_pipe["plan_uploads"].set(
                worker_id, value=m.decode_plan_uploads)
            self.g_pipe["mixed_steps"].set(
                worker_id, value=m.mixed_steps)
            self.g_pipe["stall_steps"].set(
                worker_id, value=m.decode_stall_steps)
            self.g_kv_repr["page_bytes"].set(
                worker_id, value=m.kv_page_bytes)
            self.g_kv_repr["quant_mode"].set(
                worker_id, value=m.kv_quant_bits)
            self.g_kv_repr["transfer_bytes"].set(
                worker_id, value=m.kv_transfer_bytes)
            self.g_kv_repr["transfer_fetches"].set(
                worker_id, value=m.kv_transfer_fetches)
            self.g_kv_repr["transfer_bytes_per_fetch"].set(
                worker_id,
                value=(m.kv_transfer_bytes / m.kv_transfer_fetches
                       if m.kv_transfer_fetches else 0.0))
            self.g_kv_repr["transfer_resumes"].set(
                worker_id, value=m.kv_transfer_resumes)
            self.g_kv_repr["transfer_salvaged_pages"].set(
                worker_id, value=m.kv_transfer_salvaged_pages)
            self.g_kv_repr["transfer_stale_chunks"].set(
                worker_id, value=m.kv_transfer_stale_chunks)
            self.g_kv_repr["transfer_link_timeouts"].set(
                worker_id, value=m.kv_transfer_link_timeouts)
            self.g_engine["steps"].set(worker_id, value=m.engine_steps)
            self.g_engine["recompiles"].set(
                worker_id, value=m.engine_recompiles)
            self.g_engine["tok_s"].set(worker_id, value=m.engine_tok_s)
            self.g_engine["mfu"].set(worker_id, value=m.engine_mfu)
            self.g_engine["pad_frac"].set(
                worker_id, value=m.engine_pad_frac)
            self.g_engine["host_pages_used"].set(
                worker_id, value=m.kv_host_pages_used)
            self.g_engine["host_pages_total"].set(
                worker_id, value=m.kv_host_pages_total)
            self.g_engine["disk_pages_used"].set(
                worker_id, value=m.kv_disk_pages_used)
            self.g_engine["disk_pages_total"].set(
                worker_id, value=m.kv_disk_pages_total)
            self.g_kv_stream["steps"].set(
                worker_id, value=m.kv_stream_steps)
            self.g_kv_stream["prefetch_hit"].set(
                worker_id, value=m.kv_stream_prefetch_hit)
            self.g_kv_stream["prefetch_late"].set(
                worker_id, value=m.kv_stream_prefetch_late)
            self.g_kv_stream["pages_spilled"].set(
                worker_id, value=m.kv_stream_pages_spilled)
            self.g_kv_stream["pages_quarantined"].set(
                worker_id, value=m.kv_stream_pages_quarantined)
            self.g_kv_stream["stall_steps"].set(
                worker_id, value=m.kv_stream_stall_steps)
        self.g_load_avg.set(value=endpoints.load_avg)
        self.g_load_std.set(value=endpoints.load_std)
        self.g_workers.set(value=len(endpoints.workers))

    async def _consume_hit_rate(self, sub) -> None:
        import msgpack

        from dynamo_tpu.frontend.reliability import RELIABILITY_SUBJECT
        try:
            async for subject, payload in sub:
                if subject.endswith("." + RELIABILITY_SUBJECT):
                    # "{ns}.{source}.reliability": counter snapshot from a
                    # frontend's reliability layer
                    snap = msgpack.unpackb(payload, raw=False)
                    source = subject.split(".")[-2] if subject.count(".") \
                        >= 2 else "unknown"
                    for name, gauge in self.g_reliability.items():
                        if name in snap:
                            gauge.set(source, value=float(snap[name]))
                    continue
                if not subject.endswith("." + KV_HIT_RATE_SUBJECT):
                    continue
                payload = msgpack.unpackb(payload, raw=False)
                isl = int(payload.get("isl_blocks", 0))
                overlap = int(payload.get("overlap_blocks", 0))
                self._hit_isl += isl
                self._hit_overlap += overlap
                if self._hit_isl:
                    self.g_router_hit.set(
                        value=self._hit_overlap / self._hit_isl)
        except asyncio.CancelledError:
            pass
        finally:
            aclose = getattr(sub, "aclose", None)
            if aclose is not None:
                await aclose()

    def _refresh_cp_gauges(self) -> None:
        from dynamo_tpu.runtime.cpstats import CP_STATS
        for name, value in CP_STATS.snapshot().items():
            self.g_cp[name].set(value=float(value))
        from dynamo_tpu.kv_router.stats import ROUTER_STATS
        for name, value in ROUTER_STATS.snapshot().items():
            self.g_router[name].set(value=float(value))
        from dynamo_tpu.runtime.autoscaler import AUTOSCALER_STATS
        for name, value in AUTOSCALER_STATS.snapshot().items():
            self.g_autoscaler[name].set(value=float(value))
        from dynamo_tpu.engine.kv_pool import POOL_STATS
        for name, value in POOL_STATS.snapshot().items():
            self.g_kv_pool[name].set(value=float(value))
        from dynamo_tpu.engine.pool_service import (
            REMOTE_STATS as POOL_REMOTE, RING_STATS as POOL_RING,
        )
        for name, value in POOL_REMOTE.snapshot().items():
            self.g_kv_pool_remote[name].set(value=float(value))
        for name, value in POOL_RING.snapshot().items():
            self.g_pool_ring[name].set(value=float(value))
        from dynamo_tpu.runtime.health import (
            HEALTH_STATS, HEDGE_STATS, HealthStats, HedgeStats,
        )
        for name in HealthStats.FIELDS:
            self.g_health[name].set(value=float(getattr(HEALTH_STATS, name)))
        for name in HedgeStats.FIELDS:
            self.g_hedge[name].set(value=float(getattr(HEDGE_STATS, name)))
        for cls, n in HEDGE_STATS.fired_by_class.items():
            self.g_hedge_by_class.set(cls, value=float(n))

    # -- http -----------------------------------------------------------------

    async def _serve_http(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            # bounded reads: an idle probe connection must not pin the
            # handler open (3.12 Server.wait_closed waits for ALL
            # connections, so it would hang stop())
            line = await asyncio.wait_for(reader.readline(), 5.0)
            while (await asyncio.wait_for(reader.readline(), 5.0)) \
                    not in (b"\r\n", b"\n", b""):
                pass  # drain headers
            if b"/metrics" in line:
                self._refresh_cp_gauges()
                # serving-path histograms (TTFT/ITL/queue/schedule/
                # transfer) observed in-process fold in at render, the
                # same way the frontend's /metrics appends them
                from dynamo_tpu.observability.serving import SERVING
                body = (self.registry.render() + SERVING.render()).encode()
                writer.write(
                    b"HTTP/1.1 200 OK\r\ncontent-type: text/plain; "
                    b"version=0.0.4\r\ncontent-length: %d\r\n\r\n%s"
                    % (len(body), body))
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\n"
                             b"content-length: 0\r\n\r\n")
            await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError,
                asyncio.TimeoutError):
            pass
        finally:
            writer.close()


async def _amain(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    host, port = args.coordinator.rsplit(":", 1)
    runtime = await DistributedRuntime.connect(host, int(port),
                                               "metrics-exporter")
    exporter = MetricsExporter(
        runtime, args.namespace, args.component, endpoint=args.endpoint,
        port=args.port, scrape_interval_s=args.interval)
    await exporter.start()
    log.info("metrics exporter on :%d scraping %s/%s/%s", exporter.port,
             args.namespace, args.component, args.endpoint)
    print(f"READY metrics=:{exporter.port}", flush=True)
    await asyncio.Event().wait()


def main() -> None:
    # layered defaults <- DYN_CONFIG file <- DYN_* env <- CLI flags
    # (utils/settings.py; e.g. DYN_METRICS__PORT=9095)
    from dynamo_tpu.utils.settings import load_settings
    s = load_settings({"metrics": {
        "coordinator": "127.0.0.1:6230", "port": 9091,
        "interval": 0.5}}).metrics
    ap = argparse.ArgumentParser(description="dynamo-tpu metrics exporter")
    ap.add_argument("--coordinator", default=s.coordinator)
    ap.add_argument("--namespace", required=True)
    ap.add_argument("--component", required=True)
    ap.add_argument("--endpoint", default="generate")
    ap.add_argument("--port", type=int, default=s.port)
    ap.add_argument("--interval", type=float, default=s.interval)
    args = ap.parse_args()
    from dynamo_tpu.utils.logconfig import configure_logging
    configure_logging()
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
