"""SLO burn-rate watchdog over the fleet time-series.

Layer 3 of the resource-telemetry plane (docs/OBSERVABILITY.md §7):
declarative SLO specs (TTFT p95, ITL p99, error rate, availability,
transfer-bandwidth floor — any series the rollup records) evaluated
with the multi-window burn-rate method over
observability/timeseries.py series, emitting `llm_slo_*` gauges and
event-plane alerts.

Burn-rate semantics (the Google SRE multi-window form, reduced to two
windows):

- a sample is **bad** when it violates the spec's objective
  (`mode="above"`: value > objective is bad; `"below"`: value <
  objective is bad — a bandwidth floor);
- the **burn rate** over a window is `bad_fraction / error_budget`
  where `error_budget = 1 - target`: burn 1.0 consumes the budget
  exactly at the promised rate, burn N consumes it N times too fast;
- the alert **fires** only when BOTH the short and the long window
  burn at `burn_threshold` or above — the short window gives fast
  detection, the long window keeps a 2-sample blip from paging;
- it **clears** with hysteresis: both windows must fall below
  `clear_threshold` (default half the fire threshold), so a burn
  hovering at the threshold cannot flap;
- a window with fewer than `min_samples` samples yields no verdict
  (None): the watchdog neither fires nor clears on missing data.

Degraded-mode awareness: the router's stale-snapshot degraded mode
(PR 7) is a SANCTIONED state — scheduling keeps answering on last-good
scores while the event plane catches up, and serving quality metrics
wobble by design. Specs marked `degraded_exempt=True` hold their state
frozen (no fire, no clear, `suppressed` counted) while the degraded
flag is up, so a sanctioned degradation cannot page anyone.

Everything takes explicit timestamps: the tier-1 smoke drives a
seeded, virtual-clock storm plan (`seeded_storm_plan`) through
evaluate() and asserts the fire->clear transition deterministically.
"""
from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional

from dynamo_tpu.observability.metrics import MetricsRegistry
from dynamo_tpu.observability.timeseries import SeriesStore


@dataclasses.dataclass(frozen=True)
class SloSpec:
    """One declarative SLO over one rollup series."""

    name: str                    # alert name ("ttft_p95", "bw_floor/w3")
    series: str                  # SeriesStore series the samples live in
    objective: float             # the threshold a good sample respects
    mode: str = "above"          # "above": bad when value > objective;
    #                              "below": bad when value < objective
    target: float = 0.99         # promised good fraction (error budget
    #                              = 1 - target)
    short_window_s: float = 30.0
    long_window_s: float = 300.0
    burn_threshold: float = 2.0  # fire when BOTH windows burn >= this
    clear_threshold: Optional[float] = None   # default: threshold / 2
    degraded_exempt: bool = False             # freeze during sanctioned
    #                                           degraded mode
    min_samples: int = 3         # per-window verdict floor

    def __post_init__(self):
        if self.mode not in ("above", "below"):
            raise ValueError(f"unknown mode {self.mode!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be in (0, 1)")

    @property
    def clear_at(self) -> float:
        return (self.clear_threshold if self.clear_threshold is not None
                else self.burn_threshold / 2.0)

    def is_bad(self, value: float) -> bool:
        return (value > self.objective if self.mode == "above"
                else value < self.objective)


@dataclasses.dataclass
class SloState:
    firing: bool = False
    burn_short: Optional[float] = None
    burn_long: Optional[float] = None
    transitions: int = 0         # fire->clear or clear->fire flips
    suppressed: int = 0          # evaluations frozen by degraded mode
    fired_at: Optional[float] = None
    cleared_at: Optional[float] = None


class SloWatchdog:
    """Evaluates every spec over a SeriesStore; keeps per-SLO state,
    renders `llm_slo_*` gauges, and hands alert events (fire/clear
    dicts) to `on_alert` — typically an event-plane publish
    (`wire_event_plane`)."""

    def __init__(self, store: SeriesStore, specs: List[SloSpec],
                 registry: Optional[MetricsRegistry] = None,
                 on_alert: Optional[Callable[[dict], None]] = None,
                 degraded_fn: Optional[Callable[[], bool]] = None):
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate slo names in {names}")
        self.store = store
        self.specs = list(specs)
        self.on_alert = on_alert
        self.degraded_fn = degraded_fn or _default_degraded
        self.states: Dict[str, SloState] = {
            s.name: SloState() for s in specs}
        self.alerts: List[dict] = []     # full event history (bounded)
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self._g_burn_short = r.gauge(
            "llm_slo_burn_rate_short",
            "SLO error-budget burn rate over the short window "
            "(1.0 = consuming the budget exactly at the promised rate)",
            ("slo",))
        self._g_burn_long = r.gauge(
            "llm_slo_burn_rate_long",
            "SLO error-budget burn rate over the long window", ("slo",))
        self._g_firing = r.gauge(
            "llm_slo_firing",
            "1 while the SLO's multi-window burn-rate alert is firing",
            ("slo",))
        self._g_transitions = r.gauge(
            "llm_slo_transitions",
            "cumulative fire/clear transitions of the SLO alert",
            ("slo",))
        self._g_suppressed = r.gauge(
            "llm_slo_suppressed",
            "SLO evaluations frozen by the router's sanctioned "
            "degraded mode (degraded_exempt specs)", ("slo",))

    # -- evaluation -----------------------------------------------------------

    def _burn(self, spec: SloSpec, window_s: float,
              ts: float) -> Optional[float]:
        series = self.store.get(spec.series)
        if series is None:
            return None
        frac = series.frac_where(spec.is_bad, window_s, ts,
                                 min_samples=spec.min_samples)
        if frac is None:
            return None
        return frac / (1.0 - spec.target)

    def evaluate(self, ts: float) -> List[dict]:
        """One evaluation pass at (virtual or wall) time `ts`; returns
        the alert events this pass emitted."""
        degraded = bool(self.degraded_fn())
        events: List[dict] = []
        for spec in self.specs:
            st = self.states[spec.name]
            bs = self._burn(spec, spec.short_window_s, ts)
            bl = self._burn(spec, spec.long_window_s, ts)
            st.burn_short, st.burn_long = bs, bl
            if spec.degraded_exempt and degraded:
                # sanctioned degradation: no false burn, no transition
                st.suppressed += 1
            elif st.firing:
                if (bs is not None and bl is not None
                        and bs < spec.clear_at and bl < spec.clear_at):
                    st.firing = False
                    st.cleared_at = ts
                    st.transitions += 1
                    events.append(self._event("clear", spec, st, ts))
            else:
                if (bs is not None and bl is not None
                        and bs >= spec.burn_threshold
                        and bl >= spec.burn_threshold):
                    st.firing = True
                    st.fired_at = ts
                    st.transitions += 1
                    events.append(self._event("fire", spec, st, ts))
            slo = spec.name
            self._g_burn_short.set(slo, value=bs if bs is not None else 0.0)
            self._g_burn_long.set(slo, value=bl if bl is not None else 0.0)
            self._g_firing.set(slo, value=1.0 if st.firing else 0.0)
            self._g_transitions.set(slo, value=st.transitions)
            self._g_suppressed.set(slo, value=st.suppressed)
        for ev in events:
            self.alerts.append(ev)
            if self.on_alert is not None:
                self.on_alert(ev)
        del self.alerts[:-1024]   # bounded history
        return events

    def _event(self, kind: str, spec: SloSpec, st: SloState,
               ts: float) -> dict:
        return {"event": kind, "slo": spec.name, "ts": round(ts, 3),
                "series": spec.series, "objective": spec.objective,
                "mode": spec.mode,
                "burn_short": round(st.burn_short, 3)
                if st.burn_short is not None else None,
                "burn_long": round(st.burn_long, 3)
                if st.burn_long is not None else None,
                "threshold": spec.burn_threshold}

    def firing(self) -> List[str]:
        return sorted(name for name, st in self.states.items()
                      if st.firing)

    def summary(self) -> dict:
        return {
            name: {"firing": st.firing,
                   "burn_short": st.burn_short,
                   "burn_long": st.burn_long,
                   "transitions": st.transitions,
                   "suppressed": st.suppressed}
            for name, st in sorted(self.states.items())}

    def render(self) -> str:
        return self.registry.render()


def _default_degraded() -> bool:
    """The router's stale-snapshot degraded flag (runtime/cpstats.py) —
    process-local, the sanctioned state PR 7's hysteresis manages."""
    from dynamo_tpu.runtime.cpstats import CP_STATS
    return bool(CP_STATS.router_degraded)


def wire_event_plane(watchdog: SloWatchdog, messaging, subject: str):
    """Route alert events onto the runtime event plane (the transport
    every other alert-shaped signal in this repo rides): each fire/clear
    publishes a msgpack dict on `subject`. Returns the previous
    on_alert so callers can chain."""
    import asyncio

    import msgpack
    prev = watchdog.on_alert

    def publish(ev: dict) -> None:
        if prev is not None:
            prev(ev)
        asyncio.ensure_future(
            messaging.publish(subject, msgpack.packb(ev)))

    watchdog.on_alert = publish
    return prev


def qos_slo_specs(policy=None, short_window_s: float = 30.0,
                  long_window_s: float = 300.0,
                  burn_threshold: float = 2.0,
                  min_samples: int = 3) -> List[SloSpec]:
    """Per-tenant-class SloSpecs from a QosPolicy (runtime/qos.py):
    one TTFT-p95 and one ITL-p99 spec per class, objectives taken from
    the class targets, evaluating the rollup's `qos/{class}/...`
    series (FleetRollup.scrape_once records them from the per-class
    serving histograms). All specs are degraded-exempt — the router's
    sanctioned stale-snapshot mode wobbles serving quality by design
    and must not page a tenant class (the PR-10 watchdog contract).
    This closes the PR-12 follow-on: the watchdog and the autoscaler's
    burn signals can now page and act PER CLASS."""
    from dynamo_tpu.runtime.qos import DEFAULT_POLICY
    policy = policy or DEFAULT_POLICY
    specs: List[SloSpec] = []
    for name in policy.names():
        c = policy.classes[name]
        specs.append(SloSpec(
            name=f"ttft_p95/{name}", series=f"qos/{name}/ttft_p95",
            objective=c.ttft_target_s, mode="above", target=0.9,
            short_window_s=short_window_s, long_window_s=long_window_s,
            burn_threshold=burn_threshold, min_samples=min_samples,
            degraded_exempt=True))
        specs.append(SloSpec(
            name=f"itl_p99/{name}", series=f"qos/{name}/itl_p99",
            objective=c.itl_target_s, mode="above", target=0.9,
            short_window_s=short_window_s, long_window_s=long_window_s,
            burn_threshold=burn_threshold, min_samples=min_samples,
            degraded_exempt=True))
    return specs


def seeded_storm_plan(seed: int, n_intervals: int = 120,
                      interval_s: float = 1.0,
                      storm_start: int = 40, storm_len: int = 40,
                      good_value: float = 0.05, bad_value: float = 2.0,
                      jitter: float = 0.2) -> List[tuple]:
    """Deterministic storm timeline for one series: a pure function of
    (seed, shape) -> [(ts, value)] with jittered good samples, a storm
    window of jittered bad samples, then recovery. The tier-1 smoke
    replays it through a watchdog and asserts the fire->clear
    transition lands identically every run (same seed, same events)."""
    rng = random.Random(seed)
    out = []
    for i in range(n_intervals):
        base = (bad_value if storm_start <= i < storm_start + storm_len
                else good_value)
        value = base * (1.0 + jitter * (2.0 * rng.random() - 1.0))
        out.append((i * interval_s, value))
    return out
