"""Fixed-interval ring-buffer time series: history behind the gauges.

Every `/metrics` render in this repo is a point-in-time snapshot — a
storm that degrades TTFT for 30 s and recovers is unobservable after
the fact. This module is the minimal history substrate the fleet
rollup (observability/fleet.py) and the SLO burn-rate watchdog
(observability/slo.py) sit on: bounded memory, O(1) record, explicit
timestamps everywhere so evaluation can run on a virtual clock (what
makes the SLO fire->clear smoke deterministic, tests/test_fleet.py).

- `TimeSeries`: capacity x interval ring. A sample lands in the bucket
  `ts // interval_s`; within one bucket the reduction is "last" (gauge
  semantics), "max" or "sum". Old buckets are overwritten implicitly
  (the ring slot's bucket id no longer matches), so gaps cost nothing
  and a series never grows.
- `SeriesStore`: named get-or-make registry of series (one per worker
  field, per link, per fleet aggregate).
- `Ewma`: the bandwidth smoother the TransferCostModel uses.
"""
from __future__ import annotations

from typing import Dict, List, Optional


class Ewma:
    """Exponentially-weighted moving average; `value` is None until the
    first update (consumers can distinguish 'no data' from 0)."""

    __slots__ = ("alpha", "value", "samples")

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.value: Optional[float] = None
        self.samples = 0

    def update(self, v: float) -> float:
        if self.value is None:
            self.value = float(v)
        else:
            self.value += self.alpha * (float(v) - self.value)
        self.samples += 1
        return self.value


class TimeSeries:
    """Fixed-interval ring of `capacity` buckets, `interval_s` wide.

    Explicit-`ts` API: callers pass their own clock (time.time() live,
    a virtual clock in tests/seeded plans). Reading a window only
    returns buckets whose stored id matches — stale ring slots from a
    previous wrap are invisible, so no eviction pass is ever needed."""

    __slots__ = ("interval_s", "capacity", "reduce", "_ids", "_vals",
                 "_last_bucket")

    def __init__(self, interval_s: float = 1.0, capacity: int = 600,
                 reduce: str = "last"):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        if reduce not in ("last", "max", "sum"):
            raise ValueError(f"unknown reduce {reduce!r}")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.reduce = reduce
        self._ids = [-1] * self.capacity
        self._vals = [0.0] * self.capacity
        self._last_bucket = -1

    def _bucket(self, ts: float) -> int:
        return int(ts // self.interval_s)

    def record(self, value: float, ts: float) -> None:
        b = self._bucket(ts)
        i = b % self.capacity
        if self._ids[i] == b:
            if self.reduce == "sum":
                self._vals[i] += value
            elif self.reduce == "max":
                self._vals[i] = max(self._vals[i], value)
            else:
                self._vals[i] = value
        else:
            self._ids[i] = b
            self._vals[i] = float(value)
        self._last_bucket = max(self._last_bucket, b)

    def latest(self) -> Optional[float]:
        b = self._last_bucket
        if b < 0:
            return None
        i = b % self.capacity
        return self._vals[i] if self._ids[i] == b else None

    def window(self, seconds: float, ts: float) -> List[float]:
        """Values of the buckets covering [ts - seconds, ts], oldest
        first; buckets never written (gaps) are absent, not zero."""
        b1 = self._bucket(ts)
        n = max(1, int(round(seconds / self.interval_s)))
        b0 = b1 - n + 1
        out: List[float] = []
        for b in range(max(0, b0), b1 + 1):
            i = b % self.capacity
            if self._ids[i] == b:
                out.append(self._vals[i])
        return out

    def avg(self, seconds: float, ts: float) -> Optional[float]:
        vals = self.window(seconds, ts)
        return sum(vals) / len(vals) if vals else None

    def max(self, seconds: float, ts: float) -> Optional[float]:
        vals = self.window(seconds, ts)
        return max(vals) if vals else None

    def frac_where(self, pred, seconds: float, ts: float,
                   min_samples: int = 1) -> Optional[float]:
        """Fraction of window samples where pred(value) is true; None
        when fewer than `min_samples` buckets carry data (the SLO
        evaluator treats None as 'cannot judge', never as 'good')."""
        vals = self.window(seconds, ts)
        if len(vals) < min_samples:
            return None
        return sum(1 for v in vals if pred(v)) / len(vals)


class SeriesStore:
    """Named series registry: `record(name, v, ts)` get-or-makes the
    series. Names are slash paths by convention ("fleet/workers_live",
    "worker/w0001/kv_usage", "link/w0001/bytes_per_s")."""

    def __init__(self, interval_s: float = 1.0, capacity: int = 600):
        self.interval_s = interval_s
        self.capacity = capacity
        self._series: Dict[str, TimeSeries] = {}

    def series(self, name: str, reduce: str = "last") -> TimeSeries:
        s = self._series.get(name)
        if s is None:
            s = TimeSeries(self.interval_s, self.capacity, reduce)
            self._series[name] = s
        return s

    def record(self, name: str, value: float, ts: float,
               reduce: str = "last") -> None:
        self.series(name, reduce).record(value, ts)

    def get(self, name: str) -> Optional[TimeSeries]:
        return self._series.get(name)

    def names(self, prefix: str = "") -> List[str]:
        return sorted(n for n in self._series if n.startswith(prefix))

    def __len__(self) -> int:
        return len(self._series)
