"""Per-step engine resource ledger: what is the engine DOING, over time.

PR 8 answered "where did THIS request's time go" (runtime/tracing.py);
nothing answered "what is the engine doing" — KV page occupancy per
tier, bucket-ladder padding waste, recompiles, batch occupancy, queue
depth, instantaneous tok/s — every `/metrics` render was a
point-in-time gauge with no per-step substrate behind it. The ledger is
that substrate: a bounded ring of per-step samples recorded at the
engine's commit sites, drainable as JSONL (tools/artifacts.py policy)
and folded into `llm_engine_*` gauges on every /metrics surface.

Recording discipline (the R13 deferred-recorder contract, same as
runtime/tracing.py `defer_phase`):

- **no device syncs, ever**: every sample field comes from host-side
  scheduler/allocator state the commit path already holds (allocator
  free counts, plan array shapes, deque lengths) — the ledger never
  touches a jax array;
- **disabled path is branch-only**: `record_step()` is one `if` when
  off (`DYN_LEDGER=0`), so the decode pipeline's hot-path region pays
  nothing and stays token-identical either way (it is token-identical
  with the ledger ON too — the ledger only reads, tested in
  tests/test_decode_pipeline.py);
- **bounded**: the ring overwrites oldest samples (`samples_dropped`
  counted), so a week of serving cannot grow memory.

The ledger is ON by default (like PhaseTimer): one tuple append plus
~20 plain attribute bumps per device step, at most a few thousand
steps/s — unmeasurable next to a forward pass. `DYN_LEDGER=0` turns
even that off.

Per-step sample schema (one JSONL record per step after `drain()`):
    {"ts", "dt", "kind", "rows", "rows_live", "tokens_useful",
     "tokens_padded", "kv_used", "kv_total", "host_used", "host_total",
     "disk_used", "disk_total", "waiting", "recompiles", "stream_hit",
     "stream_late", "stream_spilled", "stream_stalls", "tok_s", "mfu"}
`kind` is the step kind ("prefill" | "decode" | "mixed" | "spec" |
"stream" — the last is a tiered-KV streamed long-context step, whose
stream_* columns carry that step's window-pool prefetch deltas);
`tokens_padded` is the FULL bucket charge of the step ([Bb, Tb] or
window steps x slots) so padded - useful is the bucket-ladder waste,
attributable per step kind. `recompiles` counts NEW (program, bucket)
keys first seen at this step's dispatch (an XLA compile stall).

docs/OBSERVABILITY.md §5 documents the gauge catalog and the fleet
rollup (observability/fleet.py) that consumes the per-worker fields.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional


class LedgerStats:
    """Process-local fold target for the `llm_engine_*` gauges.

    Same pattern as runtime/cpstats.py CP_STATS: plain numeric fields
    bumped at record time, folded into Prometheus gauges at /metrics
    render by frontend/service.py. Values are process-local and
    last-writer-wins across engines in one process (cumulative fields
    add across engines) — the per-instance question /metrics answers.
    """

    FIELDS = (
        "steps_total",            # device steps committed (all kinds)
        "steps_prefill",          # pure prefill steps
        "steps_decode",           # decode windows (one per window)
        "steps_mixed",            # fused prefill+decode steps
        "steps_spec",             # speculative verify steps
        "steps_stream",           # tiered-KV streamed long-context steps
        "recompiles",             # new (program, bucket) keys dispatched
        "tokens_useful",          # committed/consumed tokens, all kinds
        "tokens_padded",          # full bucket charge, all kinds
        "useful_tokens_prefill",  # per-kind padding-waste split:
        "padded_tokens_prefill",  # prefill chunk rows
        "useful_tokens_decode",   # decode window (steps x slots)
        "padded_tokens_decode",
        "useful_tokens_mixed",    # fused steps ([Bb, Tb] charge)
        "padded_tokens_mixed",
        "kv_pages_used",          # HBM KV tier occupancy (pages)
        "kv_pages_total",
        "host_pages_used",        # host-DRAM offload tier occupancy
        "host_pages_total",
        "disk_pages_used",        # disk offload tier occupancy
        "disk_pages_total",
        "batch_rows_live",        # last step: live rows in the bucket
        "batch_rows_total",       # last step: bucket row capacity
        # tiered-KV streaming decode (engine/streaming.py), cumulative
        # across streamed steps: window-pool segments consumed from a
        # prior prefetch vs staged synchronously (the double-buffer's
        # hide-the-tier-latency verdict), pages spilled by the EWMA
        # policy, and steps that stalled on >= 1 late segment
        "stream_prefetch_hit",
        "stream_prefetch_late",
        "stream_pages_spilled",
        "stream_stall_steps",
        "queue_depth",            # last step: requests waiting
        "tok_s",                  # EWMA instantaneous useful tok/s
        "mfu",                    # tok_s * flops/token / peak (0 = no peak)
        "samples_dropped",        # ring overwrites (oldest lost)
    )

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        for name in self.FIELDS:
            setattr(self, name, 0)

    def snapshot(self) -> Dict[str, float]:
        return {name: getattr(self, name) for name in self.FIELDS}


LEDGER_STATS = LedgerStats()


def model_flops_per_token(cfg) -> float:
    """Matmul FLOPs one decoded token costs (2 x active matmul params):
    attention projections + MLP (active experts only on MoE) + lm head.
    Attention score/value FLOPs are context-dependent and excluded, so
    this is a floor — the resulting MFU is conservative. `cfg` is a
    ModelConfig (engine/config.py)."""
    d = cfg.hidden_size
    q = cfg.num_heads * cfg.head_dim
    kv = cfg.num_kv_heads * cfg.head_dim
    attn = d * q + 2 * d * kv + q * d
    mlp = 3 * d * cfg.intermediate_size
    if cfg.num_experts:
        mlp *= cfg.num_experts_per_tok
    head = d * cfg.vocab_size
    return 2.0 * (cfg.num_layers * (attn + mlp) + head)


def sampler_flops_per_token(cfg) -> float:
    """FLOPs the fused sampling tail spends per decoded token (PR 18):
    with the tail fused into the decode window program, its vocab-sized
    work (temperature scale, rank mask, gumbel draw — ~5 elementwise
    passes over [V], sort excluded as comparison-not-FLOP) executes on
    the device inside the step the ledger meters, so the MFU denominator
    counts it. Kept separate from `model_flops_per_token` (whose formula
    is load-bearing for existing consumers); the engine passes the sum."""
    return 5.0 * cfg.vocab_size


_KINDS = ("prefill", "decode", "mixed", "spec", "stream")


class StepLedger:
    """The bounded per-step sample ring + gauge fold for one engine.

    `stats` defaults to the process-global LEDGER_STATS (what /metrics
    renders); pass a private LedgerStats for isolation in tests. The
    EWMA smoothing (`tok_s`) uses alpha=0.2 over per-step instantaneous
    rates; `peak_flops` (DYN_PEAK_TFLOPS e12, or `configure()`) turns
    the rate into an MFU estimate — 0.0 when no peak is known (CPU)."""

    EWMA_ALPHA = 0.2

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None,
                 stats: Optional[LedgerStats] = None,
                 flops_per_token: float = 0.0):
        if enabled is None:
            enabled = os.environ.get("DYN_LEDGER", "1") not in ("", "0")
        if capacity is None:
            capacity = int(os.environ.get("DYN_LEDGER_CAP", "4096"))
        self.enabled = bool(enabled)
        self.capacity = max(1, int(capacity))
        self.stats = stats if stats is not None else LEDGER_STATS
        self.flops_per_token = float(flops_per_token)
        self.peak_flops = float(
            os.environ.get("DYN_PEAK_TFLOPS", "0")) * 1e12
        self._recs: List[tuple] = []
        self._pos = 0
        self.dropped = 0
        self._last_ts = 0.0
        self._tok_s = 0.0
        # per-INSTANCE cumulative counters (metrics() reads these; the
        # shared `stats` fold is process-cumulative across engines)
        self.steps = 0
        self.recompiles_total = 0
        self.useful_total = 0
        self.padded_total = 0

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None,
                  peak_tflops: Optional[float] = None) -> "StepLedger":
        if enabled is not None:
            self.enabled = enabled
        if capacity is not None:
            self.capacity = max(1, int(capacity))
            self._recs, self._pos = [], 0
        if peak_tflops is not None:
            self.peak_flops = peak_tflops * 1e12
        return self

    # -- recording (deferred-recorder discipline: host ints only) -------------

    def record_step(self, kind: str, rows: int, rows_live: int,
                    useful: int, padded: int,
                    kv_used: int, kv_total: int,
                    host_used: int, host_total: int,
                    disk_used: int, disk_total: int,
                    waiting: int, recompiles: int,
                    stream_hit: int = 0, stream_late: int = 0,
                    stream_spilled: int = 0, stream_stalls: int = 0) -> None:
        """Record one committed device step. Every argument is an
        already-known host int — the disabled path is this one branch.
        The stream_* kwargs are this step's window-pool deltas (0 on
        non-streamed kinds); they attribute the prefetch leg per step
        in the drained JSONL (tools/decode_profile.py)."""
        if not self.enabled:
            return
        now = time.monotonic()
        dt = now - self._last_ts if self._last_ts else 0.0
        self._last_ts = now
        if 0.0 < dt < 60.0:
            inst = useful / dt
            self._tok_s += self.EWMA_ALPHA * (inst - self._tok_s)
        mfu = 0.0
        if self.peak_flops > 0.0 and self.flops_per_token > 0.0:
            mfu = self._tok_s * self.flops_per_token / self.peak_flops
        rec = (now, dt, kind, rows, rows_live, useful, padded,
               kv_used, kv_total, host_used, host_total,
               disk_used, disk_total, waiting, recompiles,
               stream_hit, stream_late, stream_spilled, stream_stalls,
               self._tok_s, mfu)
        if len(self._recs) < self.capacity:
            self._recs.append(rec)
        else:
            self._recs[self._pos] = rec
            self._pos = (self._pos + 1) % self.capacity
            self.dropped += 1
        self.steps += 1
        self.recompiles_total += recompiles
        self.useful_total += useful
        self.padded_total += padded
        s = self.stats
        s.steps_total += 1
        setattr(s, "steps_" + kind, getattr(s, "steps_" + kind) + 1)
        s.recompiles += recompiles
        s.tokens_useful += useful
        s.tokens_padded += padded
        k = kind if kind in ("prefill", "decode", "mixed") else "decode"
        setattr(s, "useful_tokens_" + k,
                getattr(s, "useful_tokens_" + k) + useful)
        setattr(s, "padded_tokens_" + k,
                getattr(s, "padded_tokens_" + k) + padded)
        s.kv_pages_used = kv_used
        s.kv_pages_total = kv_total
        s.host_pages_used = host_used
        s.host_pages_total = host_total
        s.disk_pages_used = disk_used
        s.disk_pages_total = disk_total
        s.batch_rows_live = rows_live
        s.batch_rows_total = rows
        s.queue_depth = waiting
        s.stream_prefetch_hit += stream_hit
        s.stream_prefetch_late += stream_late
        s.stream_pages_spilled += stream_spilled
        s.stream_stall_steps += stream_stalls
        s.tok_s = self._tok_s
        s.mfu = mfu
        s.samples_dropped = self.dropped

    # -- derived figures (engine metrics()) -----------------------------------

    @property
    def tok_s(self) -> float:
        return self._tok_s

    @property
    def mfu(self) -> float:
        if self.peak_flops > 0.0 and self.flops_per_token > 0.0:
            return self._tok_s * self.flops_per_token / self.peak_flops
        return 0.0

    def pad_fraction(self) -> float:
        """Cumulative padded-but-useless fraction of device step tokens
        for THIS engine (bucket-ladder waste across every step kind)."""
        if self.padded_total <= 0:
            return 0.0
        return 1.0 - self.useful_total / self.padded_total

    # -- export (off the serving path) ----------------------------------------

    def __len__(self) -> int:
        return len(self._recs)

    def drain(self, clear: bool = True) -> List[Dict[str, Any]]:
        """Collect the ring, oldest first, as JSONL-ready dicts."""
        recs = self._recs[self._pos:] + self._recs[:self._pos]
        if clear:
            self._recs, self._pos = [], 0
        keys = ("ts", "dt", "kind", "rows", "rows_live", "tokens_useful",
                "tokens_padded", "kv_used", "kv_total", "host_used",
                "host_total", "disk_used", "disk_total", "waiting",
                "recompiles", "stream_hit", "stream_late",
                "stream_spilled", "stream_stalls", "tok_s", "mfu")
        out = []
        for rec in recs:
            d = dict(zip(keys, rec))
            d["ts"] = round(d["ts"], 6)
            d["dt"] = round(d["dt"], 6)
            d["tok_s"] = round(d["tok_s"], 3)
            d["mfu"] = round(d["mfu"], 6)
            out.append(d)
        return out

    def write_jsonl(self, path: str, clear: bool = True) -> int:
        """Append the drained samples to an evidence JSONL under the
        tools/artifacts.py policy; returns the record count."""
        from tools.artifacts import append_jsonl
        recs = self.drain(clear=clear)
        for rec in recs:
            append_jsonl(path, rec)
        return len(recs)

    def summary(self) -> Dict[str, Any]:
        """Aggregate view over the resident ring (fleet_storm evidence)."""
        recs = self.drain(clear=False)
        by_kind: Dict[str, int] = {}
        for r in recs:
            by_kind[r["kind"]] = by_kind.get(r["kind"], 0) + 1
        useful = sum(r["tokens_useful"] for r in recs)
        padded = sum(r["tokens_padded"] for r in recs)
        return {
            "samples": len(recs),
            "dropped": self.dropped,
            "steps_by_kind": by_kind,
            "tokens_useful": useful,
            "tokens_padded": padded,
            "pad_waste_frac": round(1.0 - useful / padded, 4)
            if padded else 0.0,
            "recompiles": sum(r["recompiles"] for r in recs),
            "kv_used_last": recs[-1]["kv_used"] if recs else 0,
            "tok_s_last": recs[-1]["tok_s"] if recs else 0.0,
        }
