"""Minimal Prometheus-compatible metrics registry.

Role-equivalent of the reference's prometheus crates usage (reference:
lib/llm/src/http/service/metrics.rs:24-130 — counters/gauges/histograms with
model/endpoint/status labels, exposed on GET /metrics in text exposition
format). Stdlib-only: the image has no prometheus_client, and the needs are
small (label vectors, histogram buckets, text rendering).
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[str, ...]


class PhaseTimer:
    """Cumulative wall-time attribution across named phases.

    The decode loop's per-window host cost was never attributed (VERDICT r5
    weak #2): plan building, array uploads, device wait, output fetch and
    commit bookkeeping all hid inside one opaque step time. The engine wraps
    each phase in `with timer.phase(name):`; tools/decode_profile.py reads
    the accumulated split and emits the committed attribution artifact.
    Overhead is two perf_counter() calls per phase — always on.

    When `trace_scope` is set (the engine sets "engine"), each phase is
    ALSO recorded as a span through the tracer's deferred recorder
    (runtime/tracing.py `defer_phase`): branch-only when tracing is
    disabled, one tuple append when enabled — the only recording form
    allowed inside `# dynalint: hot-path-begin/end` regions (R13),
    which is exactly where the engine's phase() calls live.
    """

    def __init__(self):
        self.seconds: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self.trace_scope: Optional[str] = None

    def add(self, name: str, dt: float) -> None:
        self.seconds[name] = self.seconds.get(name, 0.0) + dt
        self.counts[name] = self.counts.get(name, 0) + 1

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.add(name, dt)
            if self.trace_scope is not None:
                from dynamo_tpu.runtime.tracing import TRACER
                TRACER.defer_phase(self.trace_scope, name, dt)

    def reset(self) -> None:
        self.seconds.clear()
        self.counts.clear()

    def split(self) -> Dict[str, dict]:
        """Per-phase {seconds, count, fraction} over the accumulated total."""
        total = sum(self.seconds.values()) or 1.0
        return {
            name: {"seconds": round(s, 6),
                   "count": self.counts.get(name, 0),
                   "fraction": round(s / total, 4)}
            for name, s in sorted(self.seconds.items())
        }


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _esc(v: str) -> str:
    """Escape a label value per the Prometheus exposition format."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(names: Sequence[str], values: LabelKey,
                extra: Optional[Dict[str, str]] = None) -> str:
    parts = [f'{n}="{_esc(v)}"' for n, v in zip(names, values)]
    if extra:
        parts += [f'{n}="{_esc(v)}"' for n, v in extra.items()]
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._values: Dict[LabelKey, float] = {}

    def _check(self, labels: LabelKey):
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {labels}")

    def remove(self, *labels: str) -> None:
        """Drop one label series (e.g. a departed worker instance)."""
        self._check(labels)
        with self._lock:
            self._values.pop(labels, None)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        for labels, v in sorted(self._values.items()):
            out.append(f"{self.name}"
                       f"{_fmt_labels(self.label_names, labels)} {_fmt_value(v)}")
        if not self._values and not self.label_names:
            out.append(f"{self.name} 0")
        return out


class Counter(_Metric):
    kind = "counter"

    def inc(self, *labels: str, value: float = 1.0) -> None:
        self._check(labels)
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + value

    def get(self, *labels: str) -> float:
        return self._values.get(labels, 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, *labels: str, value: float) -> None:
        self._check(labels)
        with self._lock:
            self._values[labels] = float(value)

    def inc(self, *labels: str, value: float = 1.0) -> None:
        self._check(labels)
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + value

    def dec(self, *labels: str, value: float = 1.0) -> None:
        self.inc(*labels, value=-value)

    def get(self, *labels: str) -> float:
        return self._values.get(labels, 0.0)


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   10.0, 30.0, 60.0, float("inf"))


def _bucket_quantile(buckets, counts, total: int, q: float) -> float:
    """Shared estimator under Histogram.quantile/quantile_all; see
    quantile() for semantics. `counts` are per-bucket (not cumulative)."""
    if total <= 0 or not counts:
        return float("nan")
    if not 0.0 < q <= 1.0:
        raise ValueError(f"quantile q must be in (0, 1], got {q}")
    target = q * total
    cum = 0.0
    for i, hi in enumerate(buckets):
        prev = cum
        cum += counts[i]
        if cum >= target:
            if hi == float("inf"):
                # cannot extrapolate: largest finite bound (or NaN when
                # the ladder somehow has no finite rung)
                return buckets[i - 1] if i else float("nan")
            lo = buckets[i - 1] if i else 0.0
            if counts[i] <= 0:
                return hi
            return lo + (hi - lo) * (target - prev) / counts[i]
    return float("nan")   # unreachable: last bucket is +Inf


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, label_names=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        bl = sorted(set(buckets))
        if bl[-1] != float("inf"):
            bl.append(float("inf"))
        self.buckets = tuple(bl)
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sums: Dict[LabelKey, float] = {}
        self._totals: Dict[LabelKey, int] = {}

    def observe(self, *labels: str, value: float) -> None:
        self._check(labels)
        with self._lock:
            counts = self._counts.setdefault(labels, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            self._totals[labels] = self._totals.get(labels, 0) + 1

    def count(self, *labels: str) -> int:
        return self._totals.get(labels, 0)

    def quantile(self, q: float, *labels: str) -> float:
        """Estimate the q-quantile (0 < q <= 1) from the bucket counts —
        the promql `histogram_quantile` estimator: find the bucket the
        rank lands in, interpolate linearly inside it. Exact at bucket
        boundaries (a rank landing exactly on a bucket's cumulative
        count returns that bucket's upper bound); a rank inside the
        +Inf bucket returns the largest finite bound (the estimator
        cannot extrapolate past the ladder). NaN with no observations.
        Used by the SLO evaluator (observability/slo.py), the fleet
        rollup's serving/* series, and trace_explain --summary."""
        self._check(labels)
        with self._lock:
            counts = list(self._counts.get(labels, ()))
            total = self._totals.get(labels, 0)
        return _bucket_quantile(self.buckets, counts, total, q)

    def quantile_all(self, q: float) -> float:
        """quantile() over the SUM of every label series' buckets (the
        per-model TTFT histogram viewed fleet-wide)."""
        with self._lock:
            agg = [0] * len(self.buckets)
            for counts in self._counts.values():
                for i, c in enumerate(counts):
                    agg[i] += c
            total = sum(self._totals.values())
        return _bucket_quantile(self.buckets, agg, total, q)

    def label_values(self, label_name: str) -> List[str]:
        """Distinct observed values of one label dimension (e.g. the
        QoS classes llm_ttft_seconds has series for)."""
        i = self.label_names.index(label_name)
        with self._lock:
            return sorted({key[i] for key in self._counts})

    def quantile_label(self, q: float, label_name: str,
                       label_value: str) -> float:
        """quantile() over the sum of every series matching ONE label
        value (the per-QoS-class view of a {model, qos} histogram —
        what the fleet rollup's qos/{class}/... series record)."""
        i = self.label_names.index(label_name)
        with self._lock:
            agg = [0] * len(self.buckets)
            total = 0
            for key, counts in self._counts.items():
                if key[i] != label_value:
                    continue
                for j, c in enumerate(counts):
                    agg[j] += c
                total += self._totals.get(key, 0)
        return _bucket_quantile(self.buckets, agg, total, q)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        for labels in sorted(self._counts):
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[labels][i]
                lab = _fmt_labels(self.label_names, labels,
                                  {"le": _fmt_value(b)})
                out.append(f"{self.name}_bucket{lab} {cum}")
            plain = _fmt_labels(self.label_names, labels)
            out.append(f"{self.name}_sum{plain} "
                       f"{_fmt_value(self._sums[labels])}")
            out.append(f"{self.name}_count{plain} {self._totals[labels]}")
        return out


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "", label_names=()) -> Counter:
        return self._get_or_make(Counter, name, help_, label_names)

    def gauge(self, name: str, help_: str = "", label_names=()) -> Gauge:
        return self._get_or_make(Gauge, name, help_, label_names)

    def histogram(self, name: str, help_: str = "", label_names=(),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(Histogram, name, help_, label_names, buckets)

    def _get_or_make(self, cls, name, help_, label_names, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, label_names, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(f"{name} already registered as {m.kind}")
            return m

    def render(self) -> str:
        lines: List[str] = []
        for name in sorted(self._metrics):
            lines.extend(self._metrics[name].render())
        return "\n".join(lines) + "\n"
