"""Fleet time-series rollup + per-link KV-transfer cost model.

Layer 2 of the resource-telemetry plane (docs/OBSERVABILITY.md §6):
where the per-step ledger (observability/ledger.py) answers "what is
THIS engine doing", the rollup answers "what is the FLEET doing, over
time" — a scrape loop over the `$STATS` plane (the same WorkerMetrics
every router aggregator reads) feeding fixed-interval ring series
(observability/timeseries.py) per worker and per fleet aggregate, plus
a `TransferCostModel` of per-link KV-transfer bandwidth EWMAs fed from
the transfer backends' bytes/duration samples (the signal ROADMAP
item 3's transfer-aware router scoring consumes). The SLO watchdog
(observability/slo.py) evaluates over the same store;
`tools/fleet_top.py` renders it.

The cost model is process-global (`TRANSFER_MODEL`, the XFER_STATS
pattern): both disagg transfer backends call `observe(link, bytes,
seconds)` per completed send, so any process that ships KV pages grows
a measured bandwidth table keyed by destination engine id for free.
"""
from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Callable, Dict, List, Optional

from dynamo_tpu.observability.timeseries import Ewma, SeriesStore

log = logging.getLogger("dynamo_tpu.fleet")

# WorkerMetrics fields the rollup keeps per-worker history for (a
# deliberate subset: per-worker series cost capacity x fields buckets)
WORKER_FIELDS = (
    "kv_active_blocks", "kv_total_blocks", "request_active_slots",
    "num_requests_waiting", "gpu_cache_usage_perc", "engine_tok_s",
    "engine_mfu", "engine_pad_frac", "engine_recompiles",
    "kv_host_pages_used", "kv_transfer_bytes",
)


@dataclasses.dataclass
class TransferEstimate:
    """One router-facing cost answer. `cold` marks the no-data branch:
    the link has no measured EWMA yet and `bytes_per_s` fell back to the
    fleet median (or the configured default when NOTHING is measured) —
    never free, never infinite. Consumers must branch on it (dynalint
    R16): a cold estimate is a prior, not a measurement."""

    link: str
    seconds: float
    bytes_per_s: float
    cold: bool


class TransferCostModel:
    """Per-link KV-transfer bandwidth EWMAs, queryable by the router.

    A "link" is the destination engine/worker id of a KV page transfer
    (what `send_pages(engine_id, ...)` targets); the sample is the
    UNIQUE payload bytes of one completed send over its total wall
    seconds, so the EWMA tracks delivered goodput — integrity
    re-fetches and resume re-sends inflate the denominator without
    inflating the numerator, and a lossy link correctly estimates
    slower than its raw wire speed. `estimate(link, bytes)` is the
    router-facing query: what would shipping N bytes to this worker
    cost right now? Cold links (no EWMA yet) answer with the fleet
    median bandwidth and `cold=True` — a principled prior, neither a
    free pass nor an infinite penalty.

    The model also tracks per-destination transfer BACKLOG (bytes
    staged/in flight on sends not yet completed — `note_inflight` /
    `note_done` from the send path) and a per-link ESTIMATOR-ERROR
    EWMA (signed relative error of the pre-send estimate vs the
    actual transfer time), the diagnosis signal for routing
    regressions caused by a stale EWMA (tools/fleet_top.py,
    tools/trace_explain.py --summary)."""

    def __init__(self, alpha: float = 0.3,
                 default_bytes_per_s: float = 1e9,
                 min_sample_s: float = 1e-6):
        self.alpha = alpha
        self.default_bytes_per_s = default_bytes_per_s
        self.min_sample_s = min_sample_s
        self._links: Dict[str, Ewma] = {}
        self._err: Dict[str, Ewma] = {}
        self._inflight: Dict[str, int] = {}
        # sharded parallel transfer (disagg/remote_transfer.py): a
        # destination engine whose decode mesh spans multiple hosts is
        # a GROUP of per-host links ("{engine}/{host}"); estimate()
        # prices the parallel streams (bytes split per member, wall =
        # the slowest member) so the router sees multi-host decode
        # workers as genuinely faster targets
        self._groups: Dict[str, List[str]] = {}

    def observe(self, link: str, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds < self.min_sample_s:
            return
        ew = self._links.get(link)
        if ew is None:
            ew = self._links[link] = Ewma(self.alpha)
        else:
            # estimator error BEFORE folding the sample in: how wrong
            # would the router's estimate have been for this transfer?
            # Signed relative error: >0 = over-estimated (link faster
            # than believed), <0 = under-estimated (stale-fast EWMA —
            # the dangerous direction for routing).
            est = nbytes / max(1.0, ew.value)
            err = self._err.get(link)
            if err is None:
                err = self._err[link] = Ewma(self.alpha)
            err.update((est - seconds) / max(seconds, self.min_sample_s))
        ew.update(nbytes / seconds)

    # -- in-flight backlog (per-destination queue depth in bytes) -------------

    def note_inflight(self, link: str, nbytes: int) -> None:
        """A send of `nbytes` toward `link` started; pair with
        note_done — the delta is the router's transfer-backlog term."""
        self._inflight[link] = self._inflight.get(link, 0) + max(0, nbytes)

    def note_done(self, link: str, nbytes: int) -> None:
        left = self._inflight.get(link, 0) - max(0, nbytes)
        if left > 0:
            self._inflight[link] = left
        else:
            self._inflight.pop(link, None)

    def backlog_bytes(self, link: str) -> int:
        return self._inflight.get(link, 0)

    # -- queries --------------------------------------------------------------

    def bandwidth_bytes_per_s(self, link: str) -> float:
        ew = self._links.get(link)
        if ew is None or ew.value is None:
            # no-data branch: fleet-median prior (default when nothing
            # anywhere is measured)
            return self.fleet_median_bytes_per_s()
        return ew.value

    def fleet_median_bytes_per_s(self) -> float:
        """Median measured bandwidth across links; the cold-link prior.
        Falls back to default_bytes_per_s when no link is measured."""
        vals = sorted(ew.value for ew in self._links.values()
                      if ew.value is not None)
        if not vals:
            return self.default_bytes_per_s
        return vals[len(vals) // 2]

    def measured(self, link: str) -> bool:
        ew = self._links.get(link)
        return ew is not None and ew.samples > 0

    # -- sharded parallel streams (per-host link groups) ----------------------

    def set_group(self, link: str, members: List[str]) -> None:
        """Register `link` (a destination engine id) as a group of
        per-host member links: transfers to it ride N parallel streams,
        one per (shard, host), so its cost is the parallel composition
        of the members' — registered by the sender when discovery shows
        per-host `kv_transfer/{engine}/{host}` endpoints."""
        if len(members) >= 2:
            self._groups[link] = list(members)
        else:
            self._groups.pop(link, None)

    def group_members(self, link: str) -> Optional[List[str]]:
        return self._groups.get(link)

    def estimate(self, link: str, nbytes: int) -> TransferEstimate:
        """Cost of shipping `nbytes` to `link` now, cold-aware: a
        never-measured link answers at the fleet-median bandwidth with
        cold=True — it can never score as free (bytes always cost
        time) nor as infinitely penalized (the prior is finite).

        A GROUP link (multi-host sharded target, set_group) prices the
        parallel streams: bytes split evenly per member, wall-clock =
        the SLOWEST member's share time (the min-frontier straggler
        bound), aggregate bandwidth reported as the sum of member
        EWMAs; cold only when every member is cold (the measured/
        cold/median vocabulary of dynalint R16 applies member-wise)."""
        members = self._groups.get(link)
        if members:
            share = max(0, nbytes) / len(members)
            worst = 0.0
            agg_bw = 0.0
            cold = True
            for m in members:
                e = self.estimate(m, int(share))
                worst = max(worst, e.seconds)
                agg_bw += e.bytes_per_s
                cold = cold and e.cold
            return TransferEstimate(link=link, seconds=worst,
                                    bytes_per_s=agg_bw, cold=cold)
        cold = not self.measured(link)
        bw = max(1.0, self.bandwidth_bytes_per_s(link))
        return TransferEstimate(link=link, seconds=max(0, nbytes) / bw,
                                bytes_per_s=bw, cold=cold)

    def estimate_s(self, link: str, nbytes: int) -> float:
        # cold fallback handled inside estimate() (fleet-median prior)
        return self.estimate(link, nbytes).seconds

    def queue_s(self, link: str) -> float:
        """Drain time of the bytes already in flight toward `link` —
        the per-destination transfer-backlog term of the router score.
        Cold-safe: rides the same fleet-median prior as estimate().
        Group links (sharded multi-host targets) answer with the WORST
        member host's drain time: backlog is tracked per destination
        host, and the slowest host's queue is what gates a parallel
        transfer's min frontier."""
        members = self._groups.get(link)
        if members:
            return max((self.queue_s(m) for m in members), default=0.0)
        backlog = self.backlog_bytes(link)
        if backlog <= 0:
            return 0.0
        return self.estimate(link, backlog).seconds

    def est_err_frac(self, link: str) -> Optional[float]:
        """Signed relative estimator error EWMA for one link (None
        until a second sample exists)."""
        err = self._err.get(link)
        return err.value if err is not None else None

    def mean_abs_est_err(self) -> float:
        vals = [abs(e.value) for e in self._err.values()
                if e.value is not None]
        return sum(vals) / len(vals) if vals else 0.0

    def links(self) -> List[str]:
        return sorted(self._links)

    def snapshot(self) -> Dict[str, dict]:
        out = {}
        for link, ew in sorted(self._links.items()):
            if ew.value is None:
                continue
            row = {"bytes_per_s": round(ew.value, 1),
                   "samples": ew.samples}
            err = self._err.get(link)
            if err is not None and err.value is not None:
                row["est_err_frac"] = round(err.value, 4)
            if self._inflight.get(link):
                row["backlog_bytes"] = self._inflight[link]
            out[link] = row
        return out

    def reset(self) -> None:
        self._links.clear()
        self._err.clear()
        self._inflight.clear()
        self._groups.clear()


TRANSFER_MODEL = TransferCostModel()


def _xfer_stream_snapshot() -> Dict[str, Dict[str, int]]:
    """Per-(shard, host) transfer-stream rows for the rollup summary
    (runtime/integrity.py XFER_STATS.per_stream)."""
    from dynamo_tpu.runtime.integrity import XFER_STATS
    return XFER_STATS.stream_snapshot()


def _health_snapshot() -> dict:
    """Fail-slow table for the rollup summary: the HealthScorer's
    per-worker score/z/evidence/SLOW rows plus the process hedge
    counters (runtime/health.py)."""
    from dynamo_tpu.runtime.health import HEALTH, HEDGE_STATS
    snap = HEALTH.snapshot()
    snap["hedges"] = HEDGE_STATS.snapshot()
    return snap


def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """Minimal Prometheus text-exposition parser: family name ->
    {label-string -> value}. HELP/TYPE lines are recorded as presence
    (empty dict) so a family with no series still shows up — what the
    docs-catalog completeness test keys on. Histogram _bucket/_sum/
    _count sample names roll up under their family name."""
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                out.setdefault(parts[2], {})
            continue
        name_labels, _, value = line.rpartition(" ")
        name, labels = name_labels, ""
        if "{" in name_labels:
            name, _, rest = name_labels.partition("{")
            labels = "{" + rest
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in out:
                name = name[:-len(suffix)]
                break
        try:
            out.setdefault(name, {})[labels] = float(value)
        except ValueError:
            continue
    return out


async def scrape_http_metrics(host: str, port: int,
                              timeout_s: float = 5.0
                              ) -> Dict[str, Dict[str, float]]:
    """One GET /metrics against a frontend or exporter, parsed."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout_s)
    try:
        writer.write(b"GET /metrics HTTP/1.1\r\nhost: fleet\r\n"
                     b"connection: close\r\n\r\n")
        await asyncio.wait_for(writer.drain(), timeout_s)
        raw = await asyncio.wait_for(reader.read(), timeout_s)
    finally:
        writer.close()
    body = raw.split(b"\r\n\r\n", 1)[-1].decode(errors="replace")
    return parse_prometheus_text(body)


class FleetRollup:
    """The scrape loop: `$STATS` plane -> SeriesStore history.

    One `scrape_once(ts)` polls every live worker's WorkerMetrics
    through the runtime Client (the same fan-out KvMetricsAggregator
    does), records per-worker series for WORKER_FIELDS, fleet
    aggregates, the serving-path histogram quantiles (TTFT/ITL p95/p99
    via Histogram.quantile — the series the SLO specs evaluate), the
    control-plane health fields, and the TransferCostModel's per-link
    bandwidth EWMAs. Explicit `ts` keeps it virtual-clock testable."""

    def __init__(self, client, store: Optional[SeriesStore] = None,
                 interval_s: float = 1.0,
                 model: Optional[TransferCostModel] = None,
                 expected_workers: Optional[int] = None,
                 clock: Callable[[], float] = time.time):
        self.client = client
        self.store = store if store is not None else SeriesStore(
            interval_s=interval_s)
        self.interval_s = interval_s
        self.model = model if model is not None else TRANSFER_MODEL
        self.expected_workers = expected_workers
        self.clock = clock
        self.scrapes = 0
        self._task: Optional[asyncio.Task] = None

    async def scrape_once(self, ts: Optional[float] = None) -> dict:
        from dynamo_tpu.kv_router.scoring import WorkerMetrics
        from dynamo_tpu.runtime.cpstats import CP_STATS
        if ts is None:
            ts = self.clock()
        stats = await self.client.scrape_stats()
        rec = self.store.record
        workers: Dict[str, WorkerMetrics] = {}
        for worker_id, payload in stats.items():
            try:
                m = WorkerMetrics.from_dict(payload)
            except (TypeError, KeyError):
                continue
            workers[worker_id] = m
            for field in WORKER_FIELDS:
                rec(f"worker/{worker_id}/{field}",
                    float(getattr(m, field)), ts)
        live = len(workers)
        rec("fleet/workers_live", live, ts)
        if self.expected_workers:
            rec("fleet/availability", live / self.expected_workers, ts)
        if workers:
            rec("fleet/kv_usage_avg",
                sum(m.gpu_cache_usage_perc for m in workers.values())
                / live, ts)
            rec("fleet/waiting_total",
                sum(m.num_requests_waiting for m in workers.values()), ts)
            rec("fleet/tok_s_total",
                sum(m.engine_tok_s for m in workers.values()), ts)
            rec("fleet/recompiles_total",
                sum(m.engine_recompiles for m in workers.values()), ts)
        # per-role aggregates (ISSUE 12 satellite): the prefill/decode
        # split read once here, from the instance-key role field, so
        # the autoscaler and fleet_top consume one schema instead of
        # re-deriving it per consumer. Draining counts come from the
        # watch-maintained instance info (a draining worker still
        # answers $STATS, so it appears in `workers` too).
        from dynamo_tpu.runtime.component import (
            STATUS_DRAINING, instance_role, instance_status,
        )
        instances = getattr(self.client, "instances", None) or {}
        role_members: Dict[str, list] = {}
        role_draining: Dict[str, int] = {}
        for worker_id, info in instances.items():
            role = instance_role(info)
            if role is None:
                continue
            if instance_status(info) == STATUS_DRAINING:
                role_draining[role] = role_draining.get(role, 0) + 1
                role_members.setdefault(role, [])
            elif worker_id in workers:
                role_members.setdefault(role, []).append(workers[worker_id])
            else:
                role_members.setdefault(role, [])
        for role, members in role_members.items():
            ready = len(members)
            drn = role_draining.get(role, 0)
            rec(f"role/{role}/workers", float(ready), ts)
            rec(f"role/{role}/draining", float(drn), ts)
            rec(f"role/{role}/availability",
                ready / max(1, ready + drn), ts)
            if members:
                rec(f"role/{role}/queue_depth",
                    float(sum(m.num_requests_waiting for m in members)), ts)
                total_slots = sum(m.request_total_slots for m in members)
                rec(f"role/{role}/occupancy",
                    sum(m.request_active_slots for m in members)
                    / max(1, total_slots), ts)
        # serving-path latency quantiles (the SLO evaluator's TTFT/ITL
        # sources; Histogram.quantile — observability/metrics.py)
        from dynamo_tpu.observability.serving import SERVING
        for name, hist, q in (("serving/ttft_p95", SERVING.ttft, 0.95),
                              ("serving/itl_p99", SERVING.itl, 0.99)):
            qv = hist.quantile_all(q)
            if qv == qv:  # not NaN: at least one observation exists
                rec(name, qv, ts)
        # per-QoS-class serving series (ISSUE 14): the same quantiles
        # partitioned by the histograms' qos label — the series the
        # per-class SloSpecs (observability/slo.qos_slo_specs) evaluate,
        # so the watchdog pages per tenant class, and fleet_top's
        # per-class columns render
        for name, hist, q in (("ttft_p95", SERVING.ttft, 0.95),
                              ("itl_p99", SERVING.itl, 0.99)):
            for cls in hist.label_values("qos"):
                qv = hist.quantile_label(q, "qos", cls)
                if qv == qv:
                    rec(f"qos/{cls}/{name}", qv, ts)
        for cls in SERVING.queue_wait.label_values("qos"):
            qv = SERVING.queue_wait.quantile_label(0.95, "qos", cls)
            if qv == qv:
                rec(f"qos/{cls}/queue_wait_p95", qv, ts)
        # control-plane health + event-plane lag (degraded-mode context
        # the SLO watchdog reads)
        rec("cp/event_lag_seconds", float(CP_STATS.event_lag_seconds), ts)
        rec("cp/router_degraded", float(CP_STATS.router_degraded), ts)
        # per-link measured transfer bandwidth (the router-scoring feed)
        for link, snap in self.model.snapshot().items():
            rec(f"link/{link}/bytes_per_s", snap["bytes_per_s"], ts)
        # fail-slow health plane (runtime/health.py): per-worker score
        # series + the fleet SLOW count, so a gray failure shows up as
        # history (when did this worker start sinking?) and not just as
        # the breaker's current flag
        from dynamo_tpu.runtime.health import HEALTH
        hsnap = HEALTH.snapshot()
        for wid, row in hsnap["workers"].items():
            rec(f"health/{wid}/score", row["score"], ts)
        rec("fleet/workers_slow", float(len(hsnap["slow"])), ts)
        self.scrapes += 1
        return {"ts": ts, "workers": live,
                "links": len(self.model.links())}

    async def start(self) -> "FleetRollup":
        async def loop():
            # dynalint: backoff-ok=fixed-cadence rollup scrape; a failed
            # cycle logs and the next tick retries at the same cadence
            while True:
                try:
                    await self.scrape_once()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("fleet rollup scrape failed")
                await asyncio.sleep(self.interval_s)
        self._task = asyncio.create_task(loop())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # -- rendering / evidence -------------------------------------------------

    def summary(self, window_s: float = 60.0,
                ts: Optional[float] = None) -> dict:
        """One rollup snapshot: fleet aggregates over the window plus
        the link table (fleet_top's data source and the FLEET_r10
        evidence rows)."""
        if ts is None:
            ts = self.clock()
        st = self.store

        def agg(name):
            s = st.get(name)
            if s is None:
                return None
            return {"last": s.latest(),
                    "avg": round(a, 4) if (a := s.avg(window_s, ts))
                    is not None else None,
                    "max": s.max(window_s, ts)}

        workers = sorted({n.split("/")[1]
                          for n in st.names("worker/")})
        roles: Dict[str, dict] = {}
        for name in st.names("role/"):
            _, role, field = name.split("/", 2)
            roles.setdefault(role, {})[field] = agg(name)
        qos: Dict[str, dict] = {}
        for name in st.names("qos/"):
            _, cls, field = name.split("/", 2)
            qos.setdefault(cls, {})[field] = agg(name)
        return {
            "ts": round(ts, 3),
            "scrapes": self.scrapes,
            "workers_seen": len(workers),
            "fleet": {name.split("/", 1)[1]: agg(name)
                      for name in st.names("fleet/")},
            "serving": {name.split("/", 1)[1]: agg(name)
                        for name in st.names("serving/")},
            "cp": {name.split("/", 1)[1]: agg(name)
                   for name in st.names("cp/")},
            "roles": roles,
            "qos": qos,
            "links": self.model.snapshot(),
            # fail-slow health table (runtime/health.py HEALTH): score/
            # z/evidence/SLOW per worker plus hedge counters — what
            # fleet_top's health column renders (absent key = artifact
            # from an older build; renderers must tolerate that)
            "health": _health_snapshot(),
            # sharded parallel transfer: per-(shard, host) stream rows
            # (process-local XFER_STATS dimension — populated on the
            # in-process bench/test stacks and on any worker co-hosting
            # the rollup; fleet_top renders frontiers + the straggler)
            "xfer_streams": _xfer_stream_snapshot(),
        }

    def per_role(self) -> Dict[str, dict]:
        """Latest per-role aggregates (the controller's sensor view;
        `signals_from_rollup` folds these series plus the watchdog's
        burn state into one FleetSignals)."""
        out: Dict[str, dict] = {}
        for name in self.store.names("role/"):
            _, role, field = name.split("/", 2)
            series = self.store.get(name)
            latest = series.latest() if series is not None else None
            if latest is not None:
                out.setdefault(role, {})[field] = latest
        return out
