"""Serving-path latency histograms (the reference's request-duration
plane: `nv_llm_http_service_request_duration_seconds` and friends,
http/service/metrics.rs:24-130 — here as TTFT/ITL/queue/schedule/
transfer splits).

Before this module the `Histogram` class in observability/metrics.py had
zero call sites outside its module and TTFT/ITL existed solely inside
bench.py: when a chaos storm or a disagg handoff went wrong the only
evidence was fleet-wide gauges. These histograms are observed AT the
serving path (pipeline frame loop, router schedule, transfer backends,
admission gate) on one process-global registry, and every exposition
surface — the frontend's GET /metrics and the standalone
observability/exporter.py — appends `SERVING.render()` to its own
registry's output, the same render-time-fold pattern as the
fault/integrity/drain/cp gauges.

Observation cost is one bucket scan under a lock per event — no device
syncs, nothing on the engine step path (observations happen in the
asyncio layers around it). docs/OBSERVABILITY.md documents each series
and its bucket rationale.
"""
from __future__ import annotations

from typing import Optional

from dynamo_tpu.observability.metrics import MetricsRegistry

# Buckets sized to the quantity measured (the DEFAULT_BUCKETS ladder
# starts at 5ms — useless for a 100µs schedule decision):
TTFT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5, 5.0, 10.0, 30.0, float("inf"))
ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
               0.5, 1.0, float("inf"))
QUEUE_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                 5.0, 30.0, float("inf"))
SCHEDULE_BUCKETS = (0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                    0.01, 0.05, 0.1, float("inf"))
TRANSFER_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                    float("inf"))


class ServingMetrics:
    """The five serving-path histograms on one registry.

    - llm_ttft_seconds{model, qos}: request start -> first token frame
      (llm/pipeline._drive_n, per choice stream), partitioned by the
      request's QoS class (runtime/qos.py; unclassed requests label as
      the policy default) — the per-tenant-class series the fleet
      rollup's `qos/{class}/...` series and the per-class SloSpecs
      evaluate, so the watchdog pages per tenant class.
    - llm_itl_seconds{model, qos}: gap between successive token-carrying
      frames of one choice stream (commit-boundary ITL, the same
      boundary bench.py's churn phase measures).
    - llm_queue_wait_seconds{qos}: admission-gate wait at the frontend
      (AdmissionControl.acquire) — shed requests never observe.
    - llm_schedule_seconds: one KvRouter.schedule decision (or the
      reliability layer's fallback pick when no router is wired).
    - llm_kv_transfer_seconds: one disagg page transfer, send side
      (local or remote backend), staging -> last ack.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        r = self.registry
        self.ttft = r.histogram(
            "llm_ttft_seconds", "time to first token frame",
            ("model", "qos"), buckets=TTFT_BUCKETS)
        self.itl = r.histogram(
            "llm_itl_seconds",
            "inter-token latency at the frame boundary",
            ("model", "qos"), buckets=ITL_BUCKETS)
        self.queue_wait = r.histogram(
            "llm_queue_wait_seconds",
            "admission-gate wait before the request runs", ("qos",),
            buckets=QUEUE_BUCKETS)
        self.schedule = r.histogram(
            "llm_schedule_seconds", "worker-selection decision time",
            buckets=SCHEDULE_BUCKETS)
        self.kv_transfer = r.histogram(
            "llm_kv_transfer_seconds",
            "disagg KV page transfer, send side (stage -> last ack)",
            buckets=TRANSFER_BUCKETS)

    def render(self) -> str:
        return self.registry.render()

    def reset(self) -> None:
        """Fresh registry + histograms (test isolation helper). Call
        sites read SERVING.<name> at observation time, so re-pointing
        the attributes is enough."""
        self.__init__()


SERVING = ServingMetrics()


def ttft_quantile(q: float, qos: str = "") -> float:
    """Live TTFT quantile with per-class refinement: the per-QoS-class
    view when that class has observations, the fleet-wide view
    otherwise; NaN only when the histogram is completely empty. This is
    the hedging trigger's adaptive delay source
    (frontend/reliability.py): a hedge fires when the primary exceeds
    the q-th percentile of what the fleet is ACTUALLY serving, not a
    hand-tuned constant that rots as traffic shifts."""
    v = float("nan")
    if qos:
        v = SERVING.ttft.quantile_label(q, "qos", qos)
    if not (v == v):
        v = SERVING.ttft.quantile_all(q)
    return v
