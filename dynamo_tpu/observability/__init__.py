from dynamo_tpu.observability.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry,
)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry"]
