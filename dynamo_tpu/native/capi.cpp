// C API for native engine workers: KV-event publishing into the control
// plane, without Python in the loop.
//
// Role parity with the reference's C bindings
// (reference: lib/bindings/c/src/lib.rs:52-297 — dynamo_llm_init /
// dynamo_kv_event_publish_stored / _removed, consumed by C++ executor
// threads so a native engine can feed the KV-aware router). TPU-native
// transport: instead of a Rust runtime + NATS client, this speaks the
// framework's own length-prefixed msgpack wire (runtime/transports/wire.py)
// straight to the control-plane server's `publish` op, onto the subject
// `{ns}.{component}.kv_events` that KvIndexer subscribes to
// (kv_router/publisher.py:25).
//
// Hashing matches engine/kv_cache.py exactly: tokens_hash =
// xxh3_64(seed=1337) over each token id as 4 little-endian bytes
// (reference recipe: lib/llm/src/kv_router/indexer.rs:87-104). The system
// libxxhash provides XXH3_64bits_withSeed; prototypes declared here so no
// dev headers are needed.
//
// Thread model: one blocking socket guarded by a mutex; every publish
// awaits the server's ack frame (so errors surface and the socket can't
// fill unobserved). Matches the reference's "driven by external C++
// threads" contract.
#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" uint64_t XXH3_64bits_withSeed(const void* data, size_t len,
                                         uint64_t seed);

namespace {

constexpr uint64_t kHashSeed = 1337;

struct State {
  int fd = -1;
  std::string subject;    // "{ns}.{component}.kv_events"
  std::string worker_id;
  uint32_t block_size = 0;
  uint64_t next_msg_id = 2;  // 1 is conventionally the probe id elsewhere
  std::mutex mu;
};

State g_state;

// -- minimal msgpack writer (the subset the wire needs) ---------------------

void put_u8(std::string& b, uint8_t v) { b.push_back(static_cast<char>(v)); }

void put_be(std::string& b, uint64_t v, int bytes) {
  for (int i = bytes - 1; i >= 0; --i)
    b.push_back(static_cast<char>((v >> (8 * i)) & 0xff));
}

void pack_uint(std::string& b, uint64_t v) {
  if (v < 0x80) {
    put_u8(b, static_cast<uint8_t>(v));
  } else if (v <= 0xff) {
    put_u8(b, 0xcc); put_be(b, v, 1);
  } else if (v <= 0xffff) {
    put_u8(b, 0xcd); put_be(b, v, 2);
  } else if (v <= 0xffffffffull) {
    put_u8(b, 0xce); put_be(b, v, 4);
  } else {
    put_u8(b, 0xcf); put_be(b, v, 8);
  }
}

void pack_nil(std::string& b) { put_u8(b, 0xc0); }

void pack_str(std::string& b, const std::string& s) {
  if (s.size() < 32) {
    put_u8(b, 0xa0 | static_cast<uint8_t>(s.size()));
  } else if (s.size() <= 0xff) {
    put_u8(b, 0xd9); put_be(b, s.size(), 1);
  } else {
    put_u8(b, 0xda); put_be(b, s.size(), 2);
  }
  b.append(s);
}

void pack_bin(std::string& b, const std::string& payload) {
  if (payload.size() <= 0xff) {
    put_u8(b, 0xc4); put_be(b, payload.size(), 1);
  } else if (payload.size() <= 0xffff) {
    put_u8(b, 0xc5); put_be(b, payload.size(), 2);
  } else {
    put_u8(b, 0xc6); put_be(b, payload.size(), 4);
  }
  b.append(payload);
}

void pack_map_header(std::string& b, size_t n) {
  if (n < 16) put_u8(b, 0x80 | static_cast<uint8_t>(n));
  else { put_u8(b, 0xde); put_be(b, n, 2); }
}

void pack_array_header(std::string& b, size_t n) {
  if (n < 16) put_u8(b, 0x90 | static_cast<uint8_t>(n));
  else if (n <= 0xffff) { put_u8(b, 0xdc); put_be(b, n, 2); }
  else { put_u8(b, 0xdd); put_be(b, n, 4); }
}

// -- socket helpers ---------------------------------------------------------

bool send_all(int fd, const char* data, size_t len) {
  while (len > 0) {
    ssize_t n = ::send(fd, data, len, MSG_NOSIGNAL);
    if (n <= 0) return false;
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool recv_all(int fd, char* data, size_t len) {
  while (len > 0) {
    ssize_t n = ::recv(fd, data, len, 0);
    if (n <= 0) return false;
    data += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

// Frame the body (4-byte big-endian length prefix — wire.py pack()), send,
// and await the ack frame. The server replies {"id": rid} on success and
// {"id": rid, "error": "..."} on failure; scanning for the fixstr-encoded
// key "\xa5error" is exact for msgpack-python's output (keys < 32 chars are
// always fixstr) and avoids a full decoder here.
// A transport failure (timeout included) leaves the stream position
// unknown, so the socket is closed: later publishes fail fast until the
// caller re-inits, rather than misparsing a half-read frame.
int fail_conn() {
  ::close(g_state.fd);
  g_state.fd = -1;
  return 1;
}

int transact(const std::string& body) {
  std::string framed;
  put_be(framed, body.size(), 4);
  framed.append(body);
  if (!send_all(g_state.fd, framed.data(), framed.size())) return fail_conn();
  char hdr[4];
  if (!recv_all(g_state.fd, hdr, 4)) return fail_conn();
  uint32_t len = (static_cast<uint8_t>(hdr[0]) << 24) |
                 (static_cast<uint8_t>(hdr[1]) << 16) |
                 (static_cast<uint8_t>(hdr[2]) << 8) |
                 static_cast<uint8_t>(hdr[3]);
  if (len > (64u << 20)) return fail_conn();
  std::vector<char> reply(len);
  if (!recv_all(g_state.fd, reply.data(), len)) return fail_conn();
  static const char kErrKey[] = "\xa5" "error";
  for (size_t i = 0; i + 6 <= reply.size(); ++i)
    if (std::memcmp(reply.data() + i, kErrKey, 6) == 0) return 1;
  return 0;
}

// RouterEvent.pack() twin (kv_router/protocols.py:66-74): the payload the
// Python KvIndexer unpacks, msgpack-encoded.
std::string pack_router_event(uint64_t event_id, const std::string& data_map) {
  std::string ev;
  pack_map_header(ev, 3);
  pack_str(ev, "worker_id"); pack_str(ev, g_state.worker_id);
  pack_str(ev, "event_id"); pack_uint(ev, event_id);
  pack_str(ev, "data"); ev.append(data_map);
  return ev;
}

int publish_payload(const std::string& event_payload) {
  std::string body;
  pack_map_header(body, 4);
  pack_str(body, "id"); pack_uint(body, g_state.next_msg_id++);
  pack_str(body, "op"); pack_str(body, "publish");
  pack_str(body, "subject"); pack_str(body, g_state.subject);
  pack_str(body, "payload"); pack_bin(body, event_payload);
  return transact(body);
}

}  // namespace

extern "C" {

// Compute the content-only page hash a router derives from query tokens
// (engine/kv_cache.py tokens_hash). Exposed so C++ allocators can key
// their own structures identically.
uint64_t dyn_tokens_hash(const uint32_t* token_ids, size_t num_tokens) {
  std::string bytes;
  bytes.reserve(num_tokens * 4);
  for (size_t i = 0; i < num_tokens; ++i) {
    uint32_t t = token_ids[i];
    bytes.push_back(static_cast<char>(t & 0xff));
    bytes.push_back(static_cast<char>((t >> 8) & 0xff));
    bytes.push_back(static_cast<char>((t >> 16) & 0xff));
    bytes.push_back(static_cast<char>((t >> 24) & 0xff));
  }
  return XXH3_64bits_withSeed(bytes.data(), bytes.size(), kHashSeed);
}

// Connect to the control plane and bind this worker's event subject.
// cp_host/cp_port locate the ControlPlaneServer (the reference's etcd/NATS
// pair collapsed into one service); ns/component/worker_id mirror
// dynamo_llm_init's identity triple, kv_block_size the page geometry.
int dyn_llm_init(const char* ns, const char* component, const char* worker_id,
                 uint32_t kv_block_size, const char* cp_host, int cp_port) {
  std::lock_guard<std::mutex> lk(g_state.mu);
  if (g_state.fd >= 0) return 1;  // already initialized
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  std::string port_s = std::to_string(cp_port);
  if (getaddrinfo(cp_host, port_s.c_str(), &hints, &res) != 0 || !res)
    return 1;
  int fd = -1;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    // bounded connect: non-blocking + poll, so an unreachable control
    // plane costs seconds, not the OS connect timeout's minutes (the
    // Python twin bounds this in wire.oneshot_request)
    int flags = ::fcntl(fd, F_GETFL, 0);
    ::fcntl(fd, F_SETFL, flags | O_NONBLOCK);
    int rc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc != 0 && errno == EINPROGRESS) {
      struct pollfd pfd = {fd, POLLOUT, 0};
      int err = 0;
      socklen_t el = sizeof(err);
      if (::poll(&pfd, 1, 10000) == 1 &&
          ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &el) == 0 && err == 0)
        rc = 0;
    }
    if (rc == 0) {
      ::fcntl(fd, F_SETFL, flags);
      break;
    }
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return 1;
  // bounded publish: a wedged control plane must fail the call (and
  // release the mutex), not hang every publisher thread forever
  struct timeval tv = {30, 0};
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  ::setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  g_state.fd = fd;
  g_state.subject = std::string(ns) + "." + component + ".kv_events";
  g_state.worker_id = worker_id;
  g_state.block_size = kv_block_size;
  return 0;
}

// Publish a Stored event: a chained run of full pages. token_ids holds the
// tokens of all blocks back-to-back; num_block_tokens[i] gives block i's
// token count (must equal the init kv_block_size — partial pages are never
// indexed, engine/kv_cache.py only hashes full pages); block_ids[i] is the
// worker-assigned chained hash. parent_hash is the chained hash of the
// block preceding this run, or NULL for a root run.
int dyn_kv_event_publish_stored(uint64_t event_id, const uint32_t* token_ids,
                                const size_t* num_block_tokens,
                                const uint64_t* block_ids, size_t num_blocks,
                                const uint64_t* parent_hash) {
  std::lock_guard<std::mutex> lk(g_state.mu);
  if (g_state.fd < 0) return 1;
  std::string data;
  pack_map_header(data, 3);
  pack_str(data, "kind"); pack_str(data, "stored");
  pack_str(data, "parent_hash");
  if (parent_hash) pack_uint(data, *parent_hash); else pack_nil(data);
  pack_str(data, "blocks");
  pack_array_header(data, num_blocks);
  size_t offset = 0;
  for (size_t i = 0; i < num_blocks; ++i) {
    if (num_block_tokens[i] != g_state.block_size) return 1;
    pack_array_header(data, 2);
    pack_uint(data, block_ids[i]);
    pack_uint(data, dyn_tokens_hash(token_ids + offset, num_block_tokens[i]));
    offset += num_block_tokens[i];
  }
  return publish_payload(pack_router_event(event_id, data));
}

// Publish a Removed event: chained block hashes evicted by the allocator.
int dyn_kv_event_publish_removed(uint64_t event_id,
                                 const uint64_t* block_hashes,
                                 size_t num_blocks) {
  std::lock_guard<std::mutex> lk(g_state.mu);
  if (g_state.fd < 0) return 1;
  std::string data;
  pack_map_header(data, 2);
  pack_str(data, "kind"); pack_str(data, "removed");
  pack_str(data, "block_hashes");
  pack_array_header(data, num_blocks);
  for (size_t i = 0; i < num_blocks; ++i) pack_uint(data, block_hashes[i]);
  return publish_payload(pack_router_event(event_id, data));
}

int dyn_llm_shutdown() {
  std::lock_guard<std::mutex> lk(g_state.mu);
  if (g_state.fd < 0) return 1;
  ::close(g_state.fd);
  g_state.fd = -1;
  return 0;
}

}  // extern "C"
