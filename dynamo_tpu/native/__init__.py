"""Native (C++) runtime components, built on demand with the system g++.

The reference keeps its runtime hot paths native (Rust runtime, C++ engine
shim, CUDA block-copy kernel — SURVEY.md §2.1/§2.5/§2.8); this package holds
our native equivalents, loaded via ctypes with pure-Python fallbacks so the
framework works without a toolchain.

Build model: `g++ -O2 -shared -fPIC` at first import, cached next to the
source and rebuilt when the source is newer than the library.
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

log = logging.getLogger("dynamo_tpu.native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS = {}
# per-component extra compile/link args (after the source on the g++ line)
_EXTRA_ARGS = {
    # capi links the system xxhash (prototype declared in-source; no dev
    # headers in the image) for the tokens_hash recipe
    "capi": ["-l:libxxhash.so.0"],
}


def _build(name: str) -> Optional[str]:
    src = os.path.join(_DIR, f"{name}.cpp")
    lib = os.path.join(_DIR, f"lib{name}.so")
    try:
        if os.path.exists(lib) \
                and os.path.getmtime(lib) >= os.path.getmtime(src):
            return lib
        # compile to a private temp path and rename into place: a concurrent
        # process must never dlopen a partially-written .so
        tmp = f"{lib}.{os.getpid()}.tmp"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", tmp, src]
            + _EXTRA_ARGS.get(name, []),
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, lib)
        return lib
    except (subprocess.SubprocessError, FileNotFoundError, OSError) as e:
        stderr = getattr(e, "stderr", b"") or b""
        log.warning("native build of %s failed (%s); using Python fallback",
                    name, stderr.decode(errors="replace")[:500] or e)
        return None


def load(name: str) -> Optional[ctypes.CDLL]:
    """Build (if needed) + dlopen a native component; None on failure."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        lib_path = _build(name)
        lib = None
        if lib_path is not None:
            try:
                lib = ctypes.CDLL(lib_path)
            except OSError as e:
                log.warning("dlopen %s failed: %s", lib_path, e)
        _LIBS[name] = lib
        return lib
