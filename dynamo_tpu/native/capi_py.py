"""ctypes surface of the native C API (native/capi.cpp).

The C API exists for C++ engine workers (the reference's consumers are
TRT-LLM executor threads — reference: lib/bindings/c/src/lib.rs:52-297);
this wrapper exists so Python tests and tools can drive the exact same
shared library, proving the ABI without a C++ harness.
"""
from __future__ import annotations

import ctypes
from typing import Optional, Sequence

from dynamo_tpu import native


class CApi:
    """Typed handle over libcapi.so. Raises RuntimeError if the native
    toolchain is unavailable (this binding has no Python fallback — its
    entire point is the native path)."""

    def __init__(self):
        lib = native.load("capi")
        if lib is None:
            raise RuntimeError("native capi unavailable (g++/libxxhash?)")
        lib.dyn_tokens_hash.restype = ctypes.c_uint64
        lib.dyn_tokens_hash.argtypes = [
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_size_t]
        lib.dyn_llm_init.restype = ctypes.c_int
        lib.dyn_llm_init.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_int]
        lib.dyn_kv_event_publish_stored.restype = ctypes.c_int
        lib.dyn_kv_event_publish_stored.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_size_t), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_size_t, ctypes.POINTER(ctypes.c_uint64)]
        lib.dyn_kv_event_publish_removed.restype = ctypes.c_int
        lib.dyn_kv_event_publish_removed.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64), ctypes.c_size_t]
        lib.dyn_llm_shutdown.restype = ctypes.c_int
        lib.dyn_llm_shutdown.argtypes = []
        self._lib = lib

    def tokens_hash(self, tokens: Sequence[int]) -> int:
        arr = (ctypes.c_uint32 * len(tokens))(*tokens)
        return self._lib.dyn_tokens_hash(arr, len(tokens))

    def init(self, namespace: str, component: str, worker_id: str,
             kv_block_size: int, host: str, port: int) -> None:
        rc = self._lib.dyn_llm_init(
            namespace.encode(), component.encode(), worker_id.encode(),
            kv_block_size, host.encode(), port)
        if rc != 0:
            raise ConnectionError(
                f"dyn_llm_init failed (control plane at {host}:{port}?)")

    def publish_stored(self, event_id: int, parent_hash: Optional[int],
                       blocks: Sequence[tuple]) -> None:
        """blocks: [(block_hash, tokens), ...] with full pages only."""
        all_tokens = [t for _, toks in blocks for t in toks]
        tok_arr = (ctypes.c_uint32 * len(all_tokens))(*all_tokens)
        n_arr = (ctypes.c_size_t * len(blocks))(
            *[len(toks) for _, toks in blocks])
        id_arr = (ctypes.c_uint64 * len(blocks))(*[bh for bh, _ in blocks])
        parent = (ctypes.c_uint64(parent_hash)
                  if parent_hash is not None else None)
        rc = self._lib.dyn_kv_event_publish_stored(
            event_id, tok_arr, n_arr, id_arr, len(blocks),
            ctypes.byref(parent) if parent is not None else None)
        if rc != 0:
            raise IOError("dyn_kv_event_publish_stored failed")

    def publish_removed(self, event_id: int,
                        block_hashes: Sequence[int]) -> None:
        arr = (ctypes.c_uint64 * len(block_hashes))(*block_hashes)
        rc = self._lib.dyn_kv_event_publish_removed(
            event_id, arr, len(block_hashes))
        if rc != 0:
            raise IOError("dyn_kv_event_publish_removed failed")

    def shutdown(self) -> None:
        self._lib.dyn_llm_shutdown()
