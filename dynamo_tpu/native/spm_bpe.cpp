// Native SentencePiece-BPE encoder (score-driven bigram merging).
//
// C++ twin of dynamo_tpu/llm/gguf.py _spm_encode — the tokenize hot path
// when serving llama/mistral/gemma GGUFs (the gpt2-model path rides the HF
// `tokenizers` Rust library instead). Same role as the reference's native
// tokenization (lib/llm/src/tokenizers/ via HF tokenizers;
// gguf_tokenizer.rs builds the SPM vocab). Exact algorithm parity with the
// Python implementation: repeatedly merge the adjacent piece pair whose
// concatenation is a vocab token with the highest score (ties: leftmost),
// starting from single Unicode codepoints; unmatched pieces fall back to
// <0xXX> byte tokens, then unk.
//
// C ABI (ctypes, see native/spm.py):
//   spm_new(tok_blob, tok_offsets, n_tokens, scores, byte_ids, unk) -> handle
//   spm_encode(handle, text_utf8, text_len, out_ids, max_out) -> n_ids
//   spm_free(handle)
//
// The vocab blob is all token strings concatenated; offsets[i]..offsets[i+1]
// delimit token i (n_tokens+1 offsets). byte_ids is 256 ints (-1 = absent).

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Spm {
  std::unordered_map<std::string, int32_t> ids;
  std::vector<float> scores;
  int32_t byte_ids[256];
  int32_t unk;
};

struct HeapEnt {
  float score;    // higher merges first
  int32_t left;   // left piece index (ties: smaller index first)
  std::string merged;
};

struct HeapCmp {
  bool operator()(const HeapEnt& a, const HeapEnt& b) const {
    if (a.score != b.score) return a.score < b.score;  // max-heap on score
    return a.left > b.left;                            // then leftmost
  }
};

// split UTF-8 into codepoint-sized chunks (byte spans; invalid bytes pass
// through as single-byte pieces — the byte-fallback emits them verbatim)
void split_utf8(const char* s, int64_t n, std::vector<std::string>* out) {
  int64_t i = 0;
  while (i < n) {
    unsigned char c = static_cast<unsigned char>(s[i]);
    int len = 1;
    if ((c & 0xF8) == 0xF0) len = 4;
    else if ((c & 0xF0) == 0xE0) len = 3;
    else if ((c & 0xE0) == 0xC0) len = 2;
    if (i + len > n) len = 1;
    out->emplace_back(s + i, len);
    i += len;
  }
}

}  // namespace

extern "C" {

void* spm_new(const char* tok_blob, const int64_t* tok_offsets,
              int64_t n_tokens, const float* scores, const int32_t* byte_ids,
              int32_t unk) {
  Spm* h = new Spm();
  h->ids.reserve(static_cast<size_t>(n_tokens) * 2);
  h->scores.assign(scores, scores + n_tokens);
  for (int64_t i = 0; i < n_tokens; ++i) {
    std::string tok(tok_blob + tok_offsets[i],
                    tok_offsets[i + 1] - tok_offsets[i]);
    // first occurrence wins, matching dict(zip(tokens, ids)) lookup by
    // lowest id in the Python twin (later duplicates never shadow)
    h->ids.emplace(std::move(tok), static_cast<int32_t>(i));
  }
  std::memcpy(h->byte_ids, byte_ids, sizeof(h->byte_ids));
  h->unk = unk;
  return h;
}

void spm_free(void* handle) { delete static_cast<Spm*>(handle); }

int64_t spm_encode(void* handle, const char* text, int64_t text_len,
                   int32_t* out_ids, int64_t max_out) {
  Spm* h = static_cast<Spm*>(handle);
  std::vector<std::string> piece;
  split_utf8(text, text_len, &piece);
  const int64_t n = static_cast<int64_t>(piece.size());
  if (n == 0) return 0;

  std::vector<int64_t> nxt(n), prv(n);
  std::vector<char> alive(n, 1);
  for (int64_t i = 0; i < n; ++i) {
    nxt[i] = (i + 1 < n) ? i + 1 : -1;
    prv[i] = i - 1;
  }
  std::priority_queue<HeapEnt, std::vector<HeapEnt>, HeapCmp> heap;
  auto push = [&](int64_t i) {
    if (i < 0) return;
    int64_t j = nxt[i];
    if (j < 0) return;
    std::string merged = piece[i] + piece[j];
    auto it = h->ids.find(merged);
    if (it != h->ids.end())
      heap.push({h->scores[it->second], static_cast<int32_t>(i),
                 std::move(merged)});
  };
  for (int64_t i = 0; i + 1 < n; ++i) push(i);
  while (!heap.empty()) {
    HeapEnt e = heap.top();
    heap.pop();
    int64_t i = e.left;
    if (!alive[i]) continue;
    int64_t j = nxt[i];
    if (j < 0 || piece[i].size() + piece[j].size() != e.merged.size() ||
        piece[i] + piece[j] != e.merged)
      continue;  // stale: a neighbor already merged away
    piece[i] = std::move(e.merged);
    alive[j] = 0;
    nxt[i] = nxt[j];
    if (nxt[j] >= 0) prv[nxt[j]] = i;
    push(prv[i]);
    push(i);
  }

  int64_t count = 0;
  for (int64_t idx = 0; idx != -1; idx = nxt[idx]) {
    auto it = h->ids.find(piece[idx]);
    if (it != h->ids.end()) {
      if (count < max_out) out_ids[count] = it->second;
      ++count;
      continue;
    }
    bool got = false;
    for (unsigned char b : piece[idx]) {
      int32_t bid = h->byte_ids[b];
      if (bid >= 0) {
        if (count < max_out) out_ids[count] = bid;
        ++count;
        got = true;
      }
    }
    if (!got) {
      if (count < max_out) out_ids[count] = h->unk;
      ++count;
    }
  }
  return count;  // > max_out signals truncation (caller sizes 4*chars + 1)
}

}  // extern "C"
