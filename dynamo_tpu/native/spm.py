"""ctypes wrapper for the native SentencePiece-BPE encoder (spm_bpe.cpp).

Exact-parity twin of llm/gguf._spm_encode (same merge order, byte fallback,
unk semantics — pinned by tests/test_native_spm.py's fuzz comparison); the
GGUFTokenizer uses it automatically when the toolchain can build it and
falls back to the Python implementation otherwise. Role of the reference's
native tokenization hot path (HF `tokenizers` Rust via
lib/llm/src/tokenizers/mod.rs; SPM vocab built in gguf_tokenizer.rs).
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence

from dynamo_tpu.native import load


def available() -> bool:
    return load("spm_bpe") is not None


class NativeSpmEncoder:
    """One immutable vocab -> many encode() calls (thread-compatible: the
    native handle is read-only after construction)."""

    def __init__(self, tokens: Sequence[str], scores: Sequence[float],
                 byte_ids: Dict[int, int], unk: int):
        self._lib = load("spm_bpe")
        if self._lib is None:
            raise RuntimeError("native spm_bpe unavailable")
        lib = self._lib
        lib.spm_new.restype = ctypes.c_void_p
        lib.spm_new.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32]
        lib.spm_free.argtypes = [ctypes.c_void_p]
        lib.spm_encode.restype = ctypes.c_int64
        lib.spm_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]

        blobs = [t.encode("utf-8") for t in tokens]
        offsets = [0]
        for b in blobs:
            offsets.append(offsets[-1] + len(b))
        blob = b"".join(blobs)
        n = len(blobs)
        off_arr = (ctypes.c_int64 * (n + 1))(*offsets)
        score_arr = (ctypes.c_float * n)(*[float(s) for s in scores])
        bid_arr = (ctypes.c_int32 * 256)(*[-1] * 256)
        for b, tid in byte_ids.items():
            if 0 <= b < 256:
                bid_arr[b] = tid
        self._ptr = ctypes.c_void_p(lib.spm_new(
            blob, off_arr, n, score_arr, bid_arr, unk))

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.spm_free(ptr)

    def encode(self, prepared: str) -> List[int]:
        """`prepared` must already carry the space marker / prefix
        transform (GGUFTokenizer applies it before dispatching)."""
        raw = prepared.encode("utf-8")
        # a codepoint can byte-fall-back to <=4 ids; +1 for the unk case
        cap = 4 * len(prepared) + 1
        out = (ctypes.c_int32 * cap)()
        got = self._lib.spm_encode(self._ptr, raw, len(raw), out, cap)
        if got > cap:  # can't happen with the bound above; belt+braces
            out = (ctypes.c_int32 * got)()
            got = self._lib.spm_encode(self._ptr, raw, len(raw), out, got)
        return list(out[:got])


def make_encoder(tokens, scores, byte_ids, unk) -> Optional[NativeSpmEncoder]:
    try:
        return NativeSpmEncoder(tokens, scores, byte_ids, unk)
    except (RuntimeError, OSError):
        return None
