"""ctypes wrapper for the native radix-tree KV index (kv_indexer.cpp).

Drop-in for kv_router.indexer.RadixTree when recent-use frequency tracking
is off (the native tree tracks structure + workers only). Worker ids are
strings at the Python layer; the C layer uses u64 handles, so the wrapper
interns strings to dense ids.
"""
from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Sequence

from dynamo_tpu.kv_router.indexer import MatchResult
from dynamo_tpu.kv_router.protocols import (
    KvCacheRemoveData, KvCacheStoreData, RouterEvent,
)
from dynamo_tpu.native import load

_MAX_WORKERS = 4096


def available() -> bool:
    return load("kv_indexer") is not None


class NativeRadixTree:
    """Same surface as kv_router.indexer.RadixTree (sans frequencies)."""

    def __init__(self):
        self._lib = load("kv_indexer")
        if self._lib is None:
            raise RuntimeError("native kv_indexer unavailable")
        lib = self._lib
        lib.dtr_new.restype = ctypes.c_void_p
        lib.dtr_free.argtypes = [ctypes.c_void_p]
        u64p = ctypes.POINTER(ctypes.c_uint64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.dtr_apply_stored.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_size_t, u64p, u64p]
        lib.dtr_apply_removed.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_size_t, u64p]
        lib.dtr_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dtr_find_matches.restype = ctypes.c_size_t
        lib.dtr_find_matches.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, u64p, ctypes.c_size_t,
            u64p, u32p]
        lib.dtr_num_nodes.restype = ctypes.c_size_t
        lib.dtr_num_nodes.argtypes = [ctypes.c_void_p]
        lib.dtr_worker_block_count.restype = ctypes.c_size_t
        lib.dtr_worker_block_count.argtypes = [ctypes.c_void_p,
                                               ctypes.c_uint64]
        self._ptr = ctypes.c_void_p(lib.dtr_new())
        self._worker_ids: Dict[str, int] = {}
        self._worker_names: List[str] = []

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.dtr_free(ptr)
            self._ptr = None

    def _intern(self, worker: str) -> int:
        wid = self._worker_ids.get(worker)
        if wid is None:
            wid = len(self._worker_names) + 1  # 0 reserved
            self._worker_ids[worker] = wid
            self._worker_names.append(worker)
        return wid

    @staticmethod
    def _arr(values: Sequence[int]):
        return (ctypes.c_uint64 * len(values))(
            *[v & 0xFFFFFFFFFFFFFFFF for v in values])

    # -- RadixTree surface ----------------------------------------------------

    def apply_event(self, event: RouterEvent) -> None:
        wid = self._intern(event.worker_id)
        data = event.event.data
        if isinstance(data, KvCacheStoreData):
            blocks = data.blocks
            self._lib.dtr_apply_stored(
                self._ptr, wid, (data.parent_hash or 0) & 0xFFFFFFFFFFFFFFFF,
                len(blocks),
                self._arr([b.block_hash for b in blocks]),
                self._arr([b.tokens_hash for b in blocks]))
        elif isinstance(data, KvCacheRemoveData):
            self._lib.dtr_apply_removed(
                self._ptr, wid, len(data.block_hashes),
                self._arr(data.block_hashes))

    def find_matches(self, page_hashes: Sequence[int],
                     early_exit: bool = False,
                     now: Optional[float] = None) -> MatchResult:
        del early_exit, now  # structure-only walk
        out_w = (ctypes.c_uint64 * _MAX_WORKERS)()
        out_s = (ctypes.c_uint32 * _MAX_WORKERS)()
        n = self._lib.dtr_find_matches(
            self._ptr, len(page_hashes), self._arr(page_hashes),
            _MAX_WORKERS, out_w, out_s)
        scores = {self._worker_names[out_w[i] - 1]: int(out_s[i])
                  for i in range(n)}
        return MatchResult(scores=scores)

    def remove_worker(self, worker: str) -> None:
        wid = self._worker_ids.get(worker)
        if wid is not None:
            self._lib.dtr_remove_worker(self._ptr, wid)

    def clear_all_blocks(self, worker: str) -> None:
        self.remove_worker(worker)

    def num_nodes(self) -> int:
        return int(self._lib.dtr_num_nodes(self._ptr))

    def worker_block_count(self, worker: str) -> int:
        wid = self._worker_ids.get(worker)
        if wid is None:
            return 0
        return int(self._lib.dtr_worker_block_count(self._ptr, wid))
