// Native radix-tree KV index for KV-aware routing.
//
// C++ port of the router indexer hot path (dynamo_tpu/kv_router/indexer.py;
// reference semantics: lib/llm/src/kv_router/indexer.rs:163-388): a prefix
// tree keyed by content-only page hashes, per-node worker sets, per-worker
// block_hash -> node lookup for O(1) event application, and a prefix walk
// accumulating per-worker overlap counts. The reference keeps this in native
// code (Rust) because it sits on the per-request routing path and the
// steady-state event path; this is our native-runtime equivalent, loaded via
// ctypes (dynamo_tpu/native/__init__.py) with the Python tree as fallback.
//
// Thread model: single owner (the Python event loop) — no locking, matching
// the reference's single-threaded owner task (indexer.rs:525-593).

#include <cstdint>
#include <cstddef>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

struct Node {
    uint64_t tokens_hash;
    Node* parent;
    std::unordered_map<uint64_t, Node*> children;      // tokens_hash -> node
    std::unordered_map<uint64_t, uint64_t> workers;    // worker -> block_hash
};

struct Tree {
    Node root{0, nullptr, {}, {}};
    // worker -> (block_hash -> node)
    std::unordered_map<uint64_t, std::unordered_map<uint64_t, Node*>> lookup;

    ~Tree() { free_children(&root); }

    static void free_children(Node* n) {
        for (auto& kv : n->children) {
            free_children(kv.second);
            delete kv.second;
        }
        n->children.clear();
    }

    void maybe_prune(Node* node) {
        while (node->parent != nullptr && node->workers.empty() &&
               node->children.empty()) {
            Node* parent = node->parent;
            auto it = parent->children.find(node->tokens_hash);
            if (it != parent->children.end() && it->second == node) {
                parent->children.erase(it);
            }
            delete node;
            node = parent;
        }
    }
};

}  // namespace

extern "C" {

void* dtr_new() { return new Tree(); }

void dtr_free(void* t) { delete static_cast<Tree*>(t); }

// Stored event: attach a chained run of blocks under parent_hash (0 = root).
// Unknown parent => drop (mid-sequence pages must not forge root edges).
void dtr_apply_stored(void* tp, uint64_t worker, uint64_t parent_hash,
                      size_t n, const uint64_t* block_hashes,
                      const uint64_t* tokens_hashes) {
    Tree* t = static_cast<Tree*>(tp);
    auto& table = t->lookup[worker];
    Node* node;
    if (parent_hash == 0) {
        node = &t->root;
    } else {
        auto it = table.find(parent_hash);
        if (it == table.end()) return;
        node = it->second;
    }
    for (size_t i = 0; i < n; i++) {
        Node* child;
        auto it = node->children.find(tokens_hashes[i]);
        if (it == node->children.end()) {
            child = new Node{tokens_hashes[i], node, {}, {}};
            node->children.emplace(tokens_hashes[i], child);
        } else {
            child = it->second;
        }
        // re-store under a new block_hash: drop the stale table mapping,
        // else pruning via the new hash leaves table[old] dangling
        // (invariant: table entries are exactly {bh : node.workers[w]==bh})
        auto wit = child->workers.find(worker);
        if (wit != child->workers.end() && wit->second != block_hashes[i]) {
            table.erase(wit->second);
        }
        child->workers[worker] = block_hashes[i];
        table[block_hashes[i]] = child;
        node = child;
    }
}

void dtr_apply_removed(void* tp, uint64_t worker, size_t n,
                       const uint64_t* block_hashes) {
    Tree* t = static_cast<Tree*>(tp);
    auto lit = t->lookup.find(worker);
    if (lit == t->lookup.end()) return;
    auto& table = lit->second;
    for (size_t i = 0; i < n; i++) {
        auto it = table.find(block_hashes[i]);
        if (it == table.end()) continue;
        Node* node = it->second;
        table.erase(it);
        auto wit = node->workers.find(worker);
        if (wit != node->workers.end() && wit->second == block_hashes[i]) {
            node->workers.erase(wit);
        }
        t->maybe_prune(node);
    }
}

void dtr_remove_worker(void* tp, uint64_t worker) {
    Tree* t = static_cast<Tree*>(tp);
    auto lit = t->lookup.find(worker);
    if (lit == t->lookup.end()) return;
    std::unordered_set<Node*> nodes;
    for (auto& kv : lit->second) nodes.insert(kv.second);
    t->lookup.erase(lit);
    for (Node* node : nodes) {
        node->workers.erase(worker);
        t->maybe_prune(node);
    }
}

// Prefix walk: per-worker count of leading query pages held. Writes up to
// cap (worker, score) pairs; returns the number written.
size_t dtr_find_matches(void* tp, size_t n, const uint64_t* page_hashes,
                        size_t cap, uint64_t* out_workers,
                        uint32_t* out_scores) {
    Tree* t = static_cast<Tree*>(tp);
    std::unordered_map<uint64_t, uint32_t> scores;
    Node* node = &t->root;
    for (size_t i = 0; i < n; i++) {
        auto it = node->children.find(page_hashes[i]);
        if (it == node->children.end()) break;
        node = it->second;
        for (auto& kv : node->workers) scores[kv.first]++;
    }
    size_t written = 0;
    for (auto& kv : scores) {
        if (written >= cap) break;
        out_workers[written] = kv.first;
        out_scores[written] = kv.second;
        written++;
    }
    return written;
}

size_t dtr_num_nodes(void* tp) {
    Tree* t = static_cast<Tree*>(tp);
    std::vector<Node*> stack{&t->root};
    size_t count = 0;
    while (!stack.empty()) {
        Node* n = stack.back();
        stack.pop_back();
        count++;
        for (auto& kv : n->children) stack.push_back(kv.second);
    }
    return count - 1;  // exclude root
}

size_t dtr_worker_block_count(void* tp, uint64_t worker) {
    Tree* t = static_cast<Tree*>(tp);
    auto it = t->lookup.find(worker);
    return it == t->lookup.end() ? 0 : it->second.size();
}

}  // extern "C"
