"""llmctl: manage model->endpoint mappings in the discovery store.

Reference equivalent: launch/llmctl/src/main.rs:115-300 — `llmctl http add
chat-model <name> <endpoint>`, `list`, `remove` writing etcd keys the HTTP
frontend's model watcher consumes.

Usage:
  python -m dynamo_tpu.llmctl [--control-host H --control-port P] list
  python -m dynamo_tpu.llmctl add <name> <ns.component.endpoint> \
      [--arch tiny] [--model-type chat] [--kv-routed]
  python -m dynamo_tpu.llmctl remove <name> [--model-type chat]
"""
from __future__ import annotations

import argparse
import asyncio
import json

from dynamo_tpu.frontend.discovery import (
    list_registered_models, register_model, unregister_model,
)
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.distributed import DistributedRuntime


async def amain() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--control-host", default="127.0.0.1")
    p.add_argument("--control-port", type=int, default=5550)
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("list", help="list registered models")

    pa = sub.add_parser("add", help="register a model->endpoint mapping")
    pa.add_argument("name")
    pa.add_argument("endpoint", help="ns.component.endpoint")
    pa.add_argument("--arch", default="tiny")
    pa.add_argument("--model-type", default="chat",
                    choices=("chat", "completion", "both"))
    pa.add_argument("--kv-routed", action="store_true")

    pr = sub.add_parser("remove", help="unregister a model")
    pr.add_argument("name")
    # removal defaults to BOTH endpoints: cards registered as
    # model_type="both" (HF dirs, GGUF) would otherwise leave their
    # completion half behind
    pr.add_argument("--model-type", default="both",
                    choices=("chat", "completion", "both"))

    args = p.parse_args()
    runtime = await DistributedRuntime.connect(
        args.control_host, args.control_port)
    try:
        if args.cmd == "list":
            models = await list_registered_models(runtime.kv)
            for key, payload in sorted(models.items()):
                print(f"{key}\t{payload['namespace']}."
                      f"{payload['component']}.{payload['endpoint']}\t"
                      f"kv_routed={payload.get('kv_routed', False)}")
            if not models:
                print("(no models registered)")
        elif args.cmd == "add":
            try:
                ns, comp, ep = args.endpoint.split(".", 2)
            except ValueError:
                raise SystemExit("endpoint must be ns.component.endpoint")
            card = ModelDeploymentCard(name=args.name, arch=args.arch,
                                       model_type=args.model_type)
            await register_model(runtime.kv, args.name, ns, comp, card,
                                 endpoint=ep, model_type=args.model_type,
                                 kv_routed=args.kv_routed)
            print(f"added {args.model_type} model {args.name} -> "
                  f"{args.endpoint}")
        elif args.cmd == "remove":
            await unregister_model(runtime.kv, args.name, args.model_type)
            print(f"removed {args.model_type} model {args.name}")
    finally:
        await runtime.shutdown()


def main() -> None:
    asyncio.run(amain())


if __name__ == "__main__":
    main()
