"""Single-command launcher: `python -m dynamo_tpu.run in=X out=Y [model]`.

Role of the reference's dynamo-run binary (reference:
launch/dynamo-run/src/opt.rs:23-133 `in={http|text|stdin|batch|endpoint|
none}` x `out={engines|echo|endpoint}`, lib.rs:54-260): one process that
wires an input frontend to an engine and runs it.

Inputs:
  in=http[:port]     OpenAI HTTP server (default port 8080)
  in=text            interactive chat REPL
  in=stdin           one prompt from stdin -> streamed completion -> exit
  in=batch:FILE      JSONL prompts -> JSONL completions on stdout
  in=endpoint:NS.COMP.EP  serve the engine as a control-plane endpoint
                     (worker mode; requires --control-host/--control-port)

Outputs (engines):
  out=native         in-process JAX engine (random-init weights unless the
                     model spec is an HF dir with weights)
  out=echo           deterministic token-echo engine (no hardware)

Model spec: a named architecture from the config registry ("tiny",
"llama3-1b", "llama3-8b", "mixtral-8x7b", ...) or a path to an HF-style
model directory (config.json + tokenizer.json).
"""
from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
import uuid

from dynamo_tpu.engine.config import EngineConfig, get_model_config
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.pipeline import LocalPipeline
from dynamo_tpu.llm.worker import (
    EchoTokenEngine, NativeEngineWorker, serve_llm_worker,
)
from dynamo_tpu.protocols.openai import ChatCompletionRequest
from dynamo_tpu.runtime.engine import Context

log = logging.getLogger("dynamo_tpu.run")


def build_card(model_spec: str) -> ModelDeploymentCard:
    if os.path.isdir(model_spec):
        return ModelDeploymentCard.from_hf_dir(model_spec)
    if model_spec.endswith(".gguf") and os.path.isfile(model_spec):
        # single-file serving, as the reference's `dynamo-run model.gguf`
        # (launch/dynamo-run/src/opt.rs GGUF detection): config,
        # tokenizer, chat template, and weights all from one file
        return ModelDeploymentCard.from_gguf(model_spec)
    return ModelDeploymentCard(name=model_spec, arch=model_spec,
                               tokenizer_kind="byte")


async def build_engine(out_spec: str, card: ModelDeploymentCard, args):
    if out_spec == "echo":
        return EchoTokenEngine(delay_s=args.echo_delay)
    if out_spec != "native":
        raise SystemExit(f"unknown out={out_spec!r}")
    import glob

    from dynamo_tpu.engine.engine import NativeEngine
    from dynamo_tpu.parallel.mesh import make_mesh
    model_cfg = card.model_config()
    if args.quant:
        import dataclasses
        model_cfg = dataclasses.replace(model_cfg, quant=args.quant)
    params = None
    if card.model_path and card.model_path.endswith(".gguf"):
        from dynamo_tpu.llm.gguf import GGUFFile, load_params_from_gguf
        log.info("loading weights from %s", card.model_path)
        g = GGUFFile(card.model_path)
        try:
            # model_cfg already carries --quant, so the loader streams
            # per-projection int8 quantization during the load
            params = load_params_from_gguf(g, model_cfg)
        finally:
            g.close()
    elif card.model_path and glob.glob(
            os.path.join(card.model_path, "*.safetensors")):
        from dynamo_tpu.models.loader import load_params_from_hf
        log.info("loading weights from %s", card.model_path)
        params = load_params_from_hf(card.model_path, model_cfg)
    eng_cfg = EngineConfig(
        page_size=card.kv_page_size, num_pages=args.num_pages,
        max_slots=args.max_slots, max_prefill_chunk=args.max_prefill_chunk,
        max_model_len=min(card.context_length, model_cfg.max_model_len),
        tp=args.tp, sp=args.sp, host_pages=args.host_pages,
        spec_decode=args.spec_decode, spec_k=args.spec_k,
        spec_draft_model=args.spec_draft, kv_quant=args.kv_quant)
    n_mesh = args.tp * args.pp * args.ep * args.sp
    mesh = (make_mesh(tp=args.tp, pp=args.pp, ep=args.ep, sp=args.sp)
            if n_mesh > 1 else None)
    engine = NativeEngine(model_cfg, eng_cfg, mesh=mesh, params=params,
                          eos_token_ids=set(card.eos_token_ids))
    return await NativeEngineWorker(engine).start()


async def run_http(pipe: LocalPipeline, card, port: int) -> None:
    from dynamo_tpu.frontend.service import HttpService
    service = await HttpService(port=port).start()
    service.models.add(card.name, pipe, card.model_type)
    print(f"READY http=:{service.port} model={card.name}", flush=True)
    await asyncio.Event().wait()


async def _stream_chat(pipe: LocalPipeline, card, prompt: str,
                       max_tokens: int, out=sys.stdout) -> None:
    req = ChatCompletionRequest(
        model=card.name, stream=True, max_tokens=max_tokens,
        messages=[{"role": "user", "content": prompt}])
    ctx = Context(uuid.uuid4().hex)
    async for chunk in pipe.generate_chat(req, ctx):
        for choice in chunk.choices:
            if choice.delta.content:
                out.write(choice.delta.content)
                out.flush()
    out.write("\n")


async def run_text(pipe: LocalPipeline, card, max_tokens: int) -> None:
    print(f"model={card.name}; empty line to exit", flush=True)
    loop = asyncio.get_running_loop()
    while True:
        line = await loop.run_in_executor(None, lambda: input("> "))
        if not line.strip():
            return
        await _stream_chat(pipe, card, line, max_tokens)


async def run_stdin(pipe: LocalPipeline, card, max_tokens: int) -> None:
    prompt = sys.stdin.read().strip()
    await _stream_chat(pipe, card, prompt, max_tokens)


async def run_batch(pipe: LocalPipeline, card, path: str,
                    max_tokens: int) -> None:
    """JSONL in ({"prompt": ...}), JSONL out ({"prompt", "text"})."""
    with open(path) as f:
        prompts = [json.loads(line)["prompt"] for line in f if line.strip()]

    async def one(prompt):
        from dynamo_tpu.protocols.delta import aggregate_chat_chunks
        req = ChatCompletionRequest(
            model=card.name, stream=False, max_tokens=max_tokens,
            messages=[{"role": "user", "content": prompt}])
        chunks = [c async for c in pipe.generate_chat(req, Context())]
        agg = aggregate_chat_chunks(chunks)
        return {"prompt": prompt,
                "text": agg.choices[0].message.content,
                "finish_reason": agg.choices[0].finish_reason}

    results = await asyncio.gather(*(one(p) for p in prompts))
    for r in results:
        print(json.dumps(r), flush=True)


async def run_endpoint(engine, card, spec: str, args) -> None:
    from dynamo_tpu.frontend.discovery import register_model
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    try:
        ns, comp, ep = spec.split(".", 2)
    except ValueError:
        raise SystemExit("in=endpoint needs NS.COMPONENT.ENDPOINT")
    runtime = await DistributedRuntime.connect(
        args.control_host, args.control_port)
    served = await serve_llm_worker(runtime, ns, comp, engine, endpoint=ep,
                                    card=card)
    await register_model(runtime.kv, card.name, ns, comp, card, endpoint=ep,
                         model_type=card.model_type)
    from dynamo_tpu.llm.worker import install_graceful_drain
    install_graceful_drain(runtime, served)
    print(f"READY endpoint={spec} model={card.name}", flush=True)
    await runtime.shutdown_event.wait()


async def amain() -> None:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("io", nargs="+",
                   help="in=... out=... [model] (order-free key=value)")
    p.add_argument("--max-tokens", type=int, default=256)
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--max-slots", type=int, default=8)
    p.add_argument("--max-prefill-chunk", type=int, default=512)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (layer-sharded params + "
                        "cache, microbatched GPipe decode windows; "
                        "models/pp.py)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel shards for MoE configs "
                        "(ops/moe.py O(E/ep) dispatch)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel shards for ring-attention "
                        "prefill (ops/ring_attention.py)")
    p.add_argument("--quant", default="", choices=("", "int8"),
                   help="weight-only quantization: int8 halves weight HBM "
                        "and decode weight reads (ops/quant.py)")
    p.add_argument("--kv-quant", default="", choices=("", "int8"),
                   help="KV-cache page quantization: int8 pages + per-row "
                        "scales end-to-end (capture -> paged read -> "
                        "offload tiers -> disagg transfer), ~1.9x HBM "
                        "page capacity and ~2x fewer transfer bytes "
                        "(ops/kv_quant.py; parity-gated)")
    p.add_argument("--host-pages", type=int, default=0)
    p.add_argument("--spec-decode", default="",
                   choices=("", "ngram", "draft"),
                   help="speculative decoding: 'ngram' verifies "
                        "prompt-lookup drafts, 'draft' verifies a small "
                        "draft model's tokens, one target forward per "
                        "window (greedy plans; exact output)")
    p.add_argument("--spec-k", type=int, default=4,
                   help="draft tokens verified per forward with "
                        "--spec-decode")
    p.add_argument("--spec-draft", default="",
                   help="draft model for --spec-decode draft: a registry "
                        "name or an HF checkpoint dir (vocab must match "
                        "the served model)")
    p.add_argument("--echo-delay", type=float, default=0.0)
    p.add_argument("--control-host", default="127.0.0.1")
    p.add_argument("--control-port", type=int, default=5550)
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator (host:port) when this "
                        "engine spans processes/hosts; see DYN_COORD_ADDR")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()
    from dynamo_tpu.utils.logconfig import configure_logging
    configure_logging("debug" if args.verbose else "info")
    from dynamo_tpu.parallel.bootstrap import bootstrap_distributed
    bootstrap_distributed(args.coordinator, args.num_processes,
                          args.process_id)

    in_spec, out_spec, model_spec = "text", "echo", "tiny"
    for tok in args.io:
        if tok.startswith("in="):
            in_spec = tok[3:]
        elif tok.startswith("out="):
            out_spec = tok[4:]
        else:
            model_spec = tok

    card = build_card(model_spec)
    engine = await build_engine(out_spec, card, args)

    if in_spec.startswith("endpoint:"):
        await run_endpoint(engine, card, in_spec[len("endpoint:"):], args)
        return
    pipe = LocalPipeline(card, engine)
    if in_spec == "http" or (in_spec.startswith("http:")
                             and in_spec[5:].isdigit()):
        port = int(in_spec[5:]) if in_spec != "http" else 8080
        await run_http(pipe, card, port)
    elif in_spec == "text":
        await run_text(pipe, card, args.max_tokens)
    elif in_spec == "stdin":
        await run_stdin(pipe, card, args.max_tokens)
    elif in_spec.startswith("batch:"):
        await run_batch(pipe, card, in_spec[len("batch:"):], args.max_tokens)
    elif in_spec == "none":
        print("READY (in=none; engine built, exiting)", flush=True)
    else:
        raise SystemExit(f"unknown in={in_spec!r}")


def main() -> None:
    try:
        asyncio.run(amain())
    except (KeyboardInterrupt, EOFError):
        pass


if __name__ == "__main__":
    main()
