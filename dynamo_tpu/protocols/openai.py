"""OpenAI-compatible protocol types (chat completions + completions + models)
with the engine-extension field `ext` (our analogue of the reference's nvext,
reference: lib/llm/src/protocols/openai/nvext.rs:27-90 — ignore_eos, top_k,
repetition_penalty, greedy sampling, use_raw_prompt, annotations).
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

import pydantic


class Ext(pydantic.BaseModel):
    """Non-OpenAI extension knobs (reference nvext equivalent)."""

    ignore_eos: Optional[bool] = None
    top_k: Optional[int] = None
    repetition_penalty: Optional[float] = None
    greed_sampling: Optional[bool] = None
    use_raw_prompt: Optional[bool] = None
    annotations: Optional[List[str]] = None


class ChatMessage(pydantic.BaseModel):
    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    tool_call_id: Optional[str] = None


class ChatCompletionRequest(pydantic.BaseModel):
    model: str
    messages: List[ChatMessage]
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    n: int = 1
    stream: bool = False
    stream_options: Optional[Dict[str, Any]] = None
    stop: Optional[Union[str, List[str]]] = None
    presence_penalty: Optional[float] = None
    frequency_penalty: Optional[float] = None
    seed: Optional[int] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    user: Optional[str] = None
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    ext: Optional[Ext] = None
    # accept unknown fields permissively like the reference's serde does
    model_config = pydantic.ConfigDict(extra="allow")


class CompletionRequest(pydantic.BaseModel):
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    max_tokens: Optional[int] = 16
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    n: int = 1
    stream: bool = False
    stop: Optional[Union[str, List[str]]] = None
    seed: Optional[int] = None
    echo: bool = False
    logprobs: Optional[int] = None
    user: Optional[str] = None
    ext: Optional[Ext] = None
    model_config = pydantic.ConfigDict(extra="allow")


class Usage(pydantic.BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatChoiceDelta(pydantic.BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None


class ChatStreamChoice(pydantic.BaseModel):
    index: int = 0
    delta: ChatChoiceDelta = ChatChoiceDelta()
    finish_reason: Optional[str] = None
    # {"content": [{token, logprob, bytes, top_logprobs: [...]}, ...]}
    logprobs: Optional[Dict[str, Any]] = None


class ChatChoice(pydantic.BaseModel):
    index: int = 0
    message: ChatMessage = ChatMessage(role="assistant", content="")
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class ChatCompletionResponse(pydantic.BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int
    model: str
    choices: List[ChatChoice]
    usage: Optional[Usage] = None


class ChatCompletionChunk(pydantic.BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int
    model: str
    choices: List[ChatStreamChoice]
    usage: Optional[Usage] = None


class CompletionChoice(pydantic.BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionResponse(pydantic.BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int
    model: str
    choices: List[CompletionChoice]
    usage: Optional[Usage] = None


class ModelInfo(pydantic.BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = 0
    owned_by: str = "dynamo-tpu"


class ModelList(pydantic.BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelInfo] = []


def new_response_id(prefix: str = "cmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def now() -> int:
    return int(time.time())
