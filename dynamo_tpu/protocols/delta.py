"""OpenAI delta generation + SSE aggregation.

Reference equivalents: the delta generators turning backend frames into
chat/completion stream chunks and the aggregators folding an SSE stream back
into a unary response for non-streaming clients (reference:
lib/llm/src/protocols/openai/chat_completions/{delta,aggregator}.rs and
completions/{delta,aggregator}.rs).
"""
from __future__ import annotations

from typing import Iterable, List, Optional

from dynamo_tpu.protocols.openai import (
    ChatChoice, ChatChoiceDelta, ChatCompletionChunk, ChatCompletionResponse,
    ChatMessage, ChatStreamChoice, CompletionChoice, CompletionResponse,
    Usage, new_response_id, now,
)


class ChatDeltaGenerator:
    """Builds chat.completion.chunk frames from text deltas."""

    def __init__(self, model: str, response_id: Optional[str] = None):
        self.model = model
        self.id = response_id or new_response_id("chatcmpl")
        self.created = now()
        self._sent_role = False

    def _chunk(self, choice: ChatStreamChoice,
               usage: Optional[Usage] = None) -> ChatCompletionChunk:
        return ChatCompletionChunk(id=self.id, created=self.created,
                                   model=self.model, choices=[choice],
                                   usage=usage)

    def role_chunk(self, index: int = 0) -> ChatCompletionChunk:
        self._sent_role = True
        return self._chunk(ChatStreamChoice(
            index=index, delta=ChatChoiceDelta(role="assistant", content="")))

    def text_chunk(self, text: str, index: int = 0) -> ChatCompletionChunk:
        delta = ChatChoiceDelta(content=text)
        if not self._sent_role:
            delta.role = "assistant"
            self._sent_role = True
        return self._chunk(ChatStreamChoice(index=index, delta=delta))

    def finish_chunk(self, finish_reason: str, index: int = 0,
                     usage: Optional[Usage] = None) -> ChatCompletionChunk:
        return self._chunk(ChatStreamChoice(
            index=index, delta=ChatChoiceDelta(), finish_reason=finish_reason),
            usage)


class CompletionDeltaGenerator:
    def __init__(self, model: str, response_id: Optional[str] = None):
        self.model = model
        self.id = response_id or new_response_id("cmpl")
        self.created = now()

    def text_chunk(self, text: str, index: int = 0) -> CompletionResponse:
        return CompletionResponse(
            id=self.id, created=self.created, model=self.model,
            choices=[CompletionChoice(index=index, text=text)])

    def finish_chunk(self, finish_reason: str, index: int = 0,
                     usage: Optional[Usage] = None) -> CompletionResponse:
        return CompletionResponse(
            id=self.id, created=self.created, model=self.model,
            choices=[CompletionChoice(index=index, text="",
                                      finish_reason=finish_reason)],
            usage=usage)


def aggregate_chat_chunks(
        chunks: Iterable[ChatCompletionChunk]) -> ChatCompletionResponse:
    """Fold a chunk stream into a unary chat.completion response."""
    pieces: List[str] = []
    finish: Optional[str] = None
    rid, created, model, usage = None, None, None, None
    for c in chunks:
        rid, created, model = c.id, c.created, c.model
        usage = c.usage or usage
        for choice in c.choices:
            if choice.delta.content:
                pieces.append(choice.delta.content)
            if choice.finish_reason:
                finish = choice.finish_reason
    return ChatCompletionResponse(
        id=rid or new_response_id("chatcmpl"), created=created or now(),
        model=model or "", usage=usage,
        choices=[ChatChoice(
            message=ChatMessage(role="assistant", content="".join(pieces)),
            finish_reason=finish)])


def aggregate_completion_chunks(
        chunks: Iterable[CompletionResponse]) -> CompletionResponse:
    pieces: List[str] = []
    finish: Optional[str] = None
    rid, created, model, usage = None, None, None, None
    for c in chunks:
        rid, created, model = c.id, c.created, c.model
        usage = c.usage or usage
        for choice in c.choices:
            if choice.text:
                pieces.append(choice.text)
            if choice.finish_reason:
                finish = choice.finish_reason
    return CompletionResponse(
        id=rid or new_response_id("cmpl"), created=created or now(),
        model=model or "", usage=usage,
        choices=[CompletionChoice(text="".join(pieces), finish_reason=finish)])
