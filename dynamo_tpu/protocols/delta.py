"""OpenAI delta generation + SSE aggregation.

Reference equivalents: the delta generators turning backend frames into
chat/completion stream chunks and the aggregators folding an SSE stream back
into a unary response for non-streaming clients (reference:
lib/llm/src/protocols/openai/chat_completions/{delta,aggregator}.rs and
completions/{delta,aggregator}.rs).
"""
from __future__ import annotations

from typing import Iterable, List, Optional

from dynamo_tpu.protocols.openai import (
    ChatChoice, ChatChoiceDelta, ChatCompletionChunk, ChatCompletionResponse,
    ChatMessage, ChatStreamChoice, CompletionChoice, CompletionResponse,
    Usage, new_response_id, now,
)


class ChatDeltaGenerator:
    """Builds chat.completion.chunk frames from text deltas."""

    def __init__(self, model: str, response_id: Optional[str] = None):
        self.model = model
        self.id = response_id or new_response_id("chatcmpl")
        self.created = now()
        self._sent_role = False

    def _chunk(self, choice: ChatStreamChoice,
               usage: Optional[Usage] = None) -> ChatCompletionChunk:
        return ChatCompletionChunk(id=self.id, created=self.created,
                                   model=self.model, choices=[choice],
                                   usage=usage)

    def role_chunk(self, index: int = 0) -> ChatCompletionChunk:
        self._sent_role = True
        return self._chunk(ChatStreamChoice(
            index=index, delta=ChatChoiceDelta(role="assistant", content="")))

    def text_chunk(self, text: str, index: int = 0,
                   logprobs: Optional[dict] = None) -> ChatCompletionChunk:
        delta = ChatChoiceDelta(content=text)
        if not self._sent_role:
            delta.role = "assistant"
            self._sent_role = True
        return self._chunk(ChatStreamChoice(index=index, delta=delta,
                                            logprobs=logprobs))

    def finish_chunk(self, finish_reason: str, index: int = 0,
                     usage: Optional[Usage] = None) -> ChatCompletionChunk:
        return self._chunk(ChatStreamChoice(
            index=index, delta=ChatChoiceDelta(), finish_reason=finish_reason),
            usage)

    def usage_chunk(self, usage: Usage) -> ChatCompletionChunk:
        """Trailing usage-only chunk (OpenAI stream_options.include_usage
        sends usage with an empty choices array after all finishes)."""
        return ChatCompletionChunk(id=self.id, created=self.created,
                                   model=self.model, choices=[], usage=usage)


class CompletionDeltaGenerator:
    def __init__(self, model: str, response_id: Optional[str] = None):
        self.model = model
        self.id = response_id or new_response_id("cmpl")
        self.created = now()

    def text_chunk(self, text: str, index: int = 0,
                   logprobs: Optional[dict] = None) -> CompletionResponse:
        return CompletionResponse(
            id=self.id, created=self.created, model=self.model,
            choices=[CompletionChoice(index=index, text=text,
                                      logprobs=logprobs)])

    def finish_chunk(self, finish_reason: str, index: int = 0,
                     usage: Optional[Usage] = None) -> CompletionResponse:
        return CompletionResponse(
            id=self.id, created=self.created, model=self.model,
            choices=[CompletionChoice(index=index, text="",
                                      finish_reason=finish_reason)],
            usage=usage)

    def usage_chunk(self, usage: Usage) -> CompletionResponse:
        return CompletionResponse(id=self.id, created=self.created,
                                  model=self.model, choices=[], usage=usage)


def aggregate_chat_chunks(
        chunks: Iterable[ChatCompletionChunk]) -> ChatCompletionResponse:
    """Fold a chunk stream into a unary chat.completion response.

    Chunks are grouped by choice index so n>1 fan-out aggregates into n
    choices (reference: chat_completions/aggregator.rs does the same
    index-keyed fold)."""
    pieces: dict = {}
    finishes: dict = {}
    logprobs: dict = {}
    rid, created, model, usage = None, None, None, None
    for c in chunks:
        rid, created, model = c.id, c.created, c.model
        usage = c.usage or usage
        for choice in c.choices:
            i = choice.index
            if choice.delta.content:
                pieces.setdefault(i, []).append(choice.delta.content)
            if choice.finish_reason:
                finishes[i] = choice.finish_reason
            if choice.logprobs and choice.logprobs.get("content"):
                logprobs.setdefault(i, []).extend(
                    choice.logprobs["content"])
    idxs = sorted(set(pieces) | set(finishes)) or [0]
    return ChatCompletionResponse(
        id=rid or new_response_id("chatcmpl"), created=created or now(),
        model=model or "", usage=usage,
        choices=[ChatChoice(
            index=i,
            message=ChatMessage(role="assistant",
                                content="".join(pieces.get(i, []))),
            finish_reason=finishes.get(i),
            logprobs=({"content": logprobs[i]} if i in logprobs else None))
            for i in idxs])


def aggregate_completion_chunks(
        chunks: Iterable[CompletionResponse]) -> CompletionResponse:
    pieces: dict = {}
    finishes: dict = {}
    logprobs: dict = {}
    rid, created, model, usage = None, None, None, None
    for c in chunks:
        rid, created, model = c.id, c.created, c.model
        usage = c.usage or usage
        for choice in c.choices:
            i = choice.index
            if choice.text:
                pieces.setdefault(i, []).append(choice.text)
            if choice.finish_reason:
                finishes[i] = choice.finish_reason
            if choice.logprobs:
                agg = logprobs.setdefault(i, {
                    "text_offset": [], "token_logprobs": [], "tokens": [],
                    "top_logprobs": []})
                for k in agg:
                    agg[k].extend(choice.logprobs.get(k) or [])
    idxs = sorted(set(pieces) | set(finishes)) or [0]
    return CompletionResponse(
        id=rid or new_response_id("cmpl"), created=created or now(),
        model=model or "", usage=usage,
        choices=[CompletionChoice(
            index=i, text="".join(pieces.get(i, [])),
            finish_reason=finishes.get(i), logprobs=logprobs.get(i))
            for i in idxs])
