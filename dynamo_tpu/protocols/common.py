"""Engine-agnostic internal request/response protocol.

Role-equivalent of the reference's common protocol types (reference:
lib/llm/src/protocols/common.rs: StopConditions :205, SamplingOptions :248,
OutputOptions :320, FinishReason :52) and the backend I/O types (reference:
lib/llm/src/protocols/common/llm_backend.rs:27-126 BackendInput/
BackendOutput). Pydantic models double as validation + wire schema.
"""
from __future__ import annotations

import enum
from typing import List, Optional

import pydantic


class FinishReason(str, enum.Enum):
    STOP = "stop"            # eos or stop sequence
    LENGTH = "length"        # max_tokens reached
    CANCELLED = "cancelled"  # client disconnect / stop_generating
    ERROR = "error"
    # internal to the disaggregated path: prefill half finished; never
    # reaches the OpenAI layer (the decode side restates the final reason)
    PREFILL_DONE = "prefill_done"


class StopConditions(pydantic.BaseModel):
    max_tokens: Optional[int] = None
    stop: Optional[List[str]] = None              # visible stop strings
    stop_token_ids_hidden: Optional[List[int]] = None  # never emitted
    min_tokens: Optional[int] = None
    ignore_eos: bool = False


class SamplingOptions(pydantic.BaseModel):
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    n: int = 1


class OutputOptions(pydantic.BaseModel):
    logprobs: Optional[int] = None
    echo: bool = False


class ImagePart(pydantic.BaseModel):
    """One image's payload, positioned in the token stream.

    `offset` points at the first of the image's placeholder token ids in
    token_ids. kind="pixels": `data` is the raw float32 pixel buffer
    [H, W, 3] in [0, 1] and the receiving engine's vision tower encodes it
    (bytes ride msgpack natively; reference capability: multimodal
    engines, SURVEY.md §7 stage 7). kind="embeds": `data` is the already-
    projected patch-embed buffer [n_patches, D_text] float32 and `salt`
    carries the pixel-content hash the page-hash chain needs — the
    disaggregated decode worker's mm_transfer="embeds" mode forwards its
    own tower's output so the prefill side skips the vision tower
    entirely (VERDICT r3 weak #6: pixels-travel re-encoded on both
    sides; embeds-travel encodes once and often ships fewer bytes for
    large images)."""

    offset: int
    shape: List[int]          # [H, W, 3] pixels | [n_patches, D] embeds
    dtype: str = "float32"
    data: bytes
    kind: str = "pixels"      # "pixels" | "embeds"
    salt: Optional[int] = None  # pixel-content hash (embeds kind)


class PreprocessedRequest(pydantic.BaseModel):
    """What the frontend/processor sends to a worker (token-level request).

    Counterpart of the reference's BackendInput (token_ids, sampling, stop,
    eos ids, mdc checksum).
    """

    request_id: str
    token_ids: List[int]
    sampling: SamplingOptions = SamplingOptions()
    stop: StopConditions = StopConditions()
    output: OutputOptions = OutputOptions()
    eos_token_ids: List[int] = []
    model: str = ""
    mdc_sum: str = ""
    annotations: List[str] = []
    # multimodal: images to mix into the prefill at placeholder positions
    mm_parts: Optional[List[ImagePart]] = None
    # mid-stream migration (frontend/reliability.py): token_ids carries the
    # original prompt PLUS the last `resume_committed` tokens already
    # streamed to the client by a previous (dead) worker. The receiving
    # engine re-prefills the whole sequence and continues decoding; its
    # stop budgets (max_tokens/min_tokens) are interpreted as the ORIGINAL
    # request's, so the worker charges the committed tokens against them
    # (llm/worker._to_engine_request). Greedy continuations are
    # token-identical to an uninterrupted run (tests/test_chaos.py).
    resume_committed: int = 0


class EngineOutput(pydantic.BaseModel):
    """One streamed frame from a worker back to the frontend.

    Counterpart of the reference's BackendOutput/LLMEngineOutput (which also
    carries per-token log_probs, lib/llm/src/protocols/common/llm_backend.rs).
    """

    token_ids: List[int] = []
    text: Optional[str] = None
    cum_log_probs: Optional[float] = None
    # parallel to token_ids when the request asked for logprobs
    log_probs: Optional[List[float]] = None
    # per token: the top-k alternatives as [token_id, logprob] pairs
    top_logprobs: Optional[List[List[List[float]]]] = None
    finish_reason: Optional[FinishReason] = None
    # ERROR frames only — False: deterministic per-REQUEST rejection
    # (admission/validation); re-dispatching elsewhere fails identically,
    # so the reliability layer forwards it instead of retrying. True/None:
    # instance-scoped failure (engine died, out of capacity); retryable on
    # another worker.
    retryable: Optional[bool] = None
