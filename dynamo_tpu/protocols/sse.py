"""Server-Sent Events codec + the Annotated frame envelope.

Reference equivalents: the SSE codec (reference: lib/llm/src/protocols/
codec.rs) and the `Annotated{data,id,event,comment}` envelope aligned with
SSE semantics (reference: lib/runtime/src/protocols/annotated.rs:32-80) used
to carry both data frames and request-introspection annotations
(`token_ids`, `formatted_prompt`) through the stream.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Iterator, List, Optional


@dataclasses.dataclass
class Annotated:
    data: Optional[Any] = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: Optional[List[str]] = None

    def is_error(self) -> bool:
        return self.event == "error"

    @classmethod
    def from_error(cls, message: str) -> "Annotated":
        return cls(event="error", comment=[message])

    @classmethod
    def annotation(cls, name: str, value: Any) -> "Annotated":
        return cls(event=name, data=value)

    def to_wire(self) -> dict:
        out = {}
        for f in ("data", "id", "event", "comment"):
            v = getattr(self, f)
            if v is not None:
                out[f] = v
        return out

    @classmethod
    def from_wire(cls, d: dict) -> "Annotated":
        return cls(data=d.get("data"), id=d.get("id"), event=d.get("event"),
                   comment=d.get("comment"))


@dataclasses.dataclass
class SseEvent:
    data: Optional[str] = None
    event: Optional[str] = None
    id: Optional[str] = None
    comments: List[str] = dataclasses.field(default_factory=list)

    @property
    def is_done(self) -> bool:
        return self.data is not None and self.data.strip() == "[DONE]"


def encode_event(ev: SseEvent) -> str:
    """Encode one SSE event block (terminated by a blank line)."""
    lines = []
    for c in ev.comments:
        lines.append(f": {c}")
    if ev.event:
        lines.append(f"event: {ev.event}")
    if ev.id:
        lines.append(f"id: {ev.id}")
    if ev.data is not None:
        for part in ev.data.split("\n"):
            lines.append(f"data: {part}")
    return "\n".join(lines) + "\n\n"


def encode_json_data(obj: Any) -> str:
    return encode_event(SseEvent(data=json.dumps(obj, separators=(",", ":"))))


DONE_FRAME = "data: [DONE]\n\n"


def decode_stream(text: str) -> Iterator[SseEvent]:
    """Parse SSE text into events; tolerates multi-line data, comments,
    and unknown fields (the edge cases the reference replay tests cover)."""
    for block in text.split("\n\n"):
        if not block.strip():
            continue
        ev = SseEvent()
        data_lines: List[str] = []
        for line in block.split("\n"):
            if not line:
                continue
            if line.startswith(":"):
                ev.comments.append(line[1:].strip())
            elif line.startswith("data:"):
                data_lines.append(line[5:].lstrip(" "))
            elif line.startswith("event:"):
                ev.event = line[6:].strip()
            elif line.startswith("id:"):
                ev.id = line[3:].strip()
            # unknown fields ignored per SSE spec
        if data_lines:
            ev.data = "\n".join(data_lines)
        yield ev
