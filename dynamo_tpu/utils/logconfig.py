"""Env-filtered logging with an optional JSONL sink.

The reference configures tracing subscribers from `DYN_LOG` (per-target
level filters, `RUST_LOG` grammar) and flips between pretty and JSONL
output via `DYN_LOGGING_JSONL` (reference: lib/runtime/src/logging.rs:16-120).
This is the Python equivalent over the stdlib logging tree:

- ``DYN_LOG``: comma-separated directives, each either a bare level
  (sets the default) or ``logger.prefix=level``. Later directives win.
  Example: ``DYN_LOG=info,dynamo_tpu.engine=debug,dynamo_tpu.kv_router=warning``
- ``DYN_LOGGING_JSONL=1``: one JSON object per line on stderr
  (``ts``, ``level``, ``target``, ``message``, plus exception text),
  machine-ingestable (fluentd/vector), matching the reference's JSONL
  mode's role.
- ``DYN_LOG_FILE``: also append records to this path.

configure_logging() is idempotent (re-running reconfigures rather than
duplicating handlers) and is called by every launch binary (run.py,
llmctl, frontend.serve, kv_router.main, observability.exporter, the
control-plane server).
"""
from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Dict, Optional, Tuple

_LEVELS = {
    "trace": logging.DEBUG,  # stdlib has no TRACE; map to DEBUG
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
    "off": logging.CRITICAL + 10,
}

_CONFIGURED_MARK = "_dynamo_tpu_handler"


class JsonlFormatter(logging.Formatter):
    """One JSON object per record: {"ts", "level", "target", "message"}."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
                  + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def parse_filter(spec: str) -> Tuple[int, Dict[str, int]]:
    """Parse a DYN_LOG directive list -> (default_level, {prefix: level}).

    Unknown directives are ignored with a warning on stderr rather than
    failing startup (a typo in an env var must not take the service down).
    """
    default = logging.INFO
    per_target: Dict[str, int] = {}
    for raw in spec.split(","):
        item = raw.strip()
        if not item:
            continue
        if "=" in item:
            target, _, lvl = item.partition("=")
            level = _LEVELS.get(lvl.strip().lower())
            if level is None:
                print(f"DYN_LOG: unknown level {lvl!r} in {item!r}; ignored",
                      file=sys.stderr)
                continue
            per_target[target.strip()] = level
        else:
            level = _LEVELS.get(item.lower())
            if level is None:
                print(f"DYN_LOG: unknown directive {item!r}; ignored",
                      file=sys.stderr)
                continue
            default = level
    return default, per_target


def configure_logging(default: Optional[str] = None) -> None:
    """Install handlers/levels from DYN_LOG / DYN_LOGGING_JSONL / DYN_LOG_FILE.

    `default` seeds the default level when DYN_LOG names none (binaries
    pass their --log-level flag here; env still wins for per-target
    directives).
    """
    spec = os.environ.get("DYN_LOG", "")
    base, per_target = parse_filter(spec)
    if default is not None and not any(
            item.strip() and "=" not in item for item in spec.split(",")):
        base = _LEVELS.get(default.lower(), base)

    root = logging.getLogger()
    # idempotent: drop only handlers we installed earlier (closing them —
    # a reconfigure must not leak the DYN_LOG_FILE descriptor or strand
    # buffered records)
    for h in list(root.handlers):
        if getattr(h, _CONFIGURED_MARK, False):
            root.removeHandler(h)
            h.close()

    jsonl = os.environ.get("DYN_LOGGING_JSONL", "") not in ("", "0", "false")
    if jsonl:
        formatter: logging.Formatter = JsonlFormatter()
    else:
        formatter = logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s: %(message)s")
    handlers = [logging.StreamHandler(sys.stderr)]
    log_file = os.environ.get("DYN_LOG_FILE")
    if log_file:
        handlers.append(logging.FileHandler(log_file))
    for h in handlers:
        h.setFormatter(formatter)
        setattr(h, _CONFIGURED_MARK, True)
        root.addHandler(h)
    root.setLevel(base)

    # reset levels set by a previous configure_logging call so directives
    # removed from DYN_LOG don't linger across reconfigures (tests)
    for name in list(logging.Logger.manager.loggerDict):
        lg = logging.Logger.manager.loggerDict[name]
        if isinstance(lg, logging.Logger) \
                and getattr(lg, _CONFIGURED_MARK, False):
            lg.setLevel(logging.NOTSET)
            delattr(lg, _CONFIGURED_MARK)
    for target, level in per_target.items():
        lg = logging.getLogger(target)
        lg.setLevel(level)
        setattr(lg, _CONFIGURED_MARK, True)
