"""Layered runtime settings: defaults <- config file <- DYN_* env.

The reference layers its RuntimeConfig through figment — struct defaults,
then a TOML file, then `DYN_*` environment variables, later layers winning
(reference: lib/runtime/src/config.rs:81-105). This is the same contract
for the Python runtime, shared by the five launch binaries:

    settings = load_settings(
        defaults={"control_plane": {"host": "127.0.0.1", "port": 7411},
                  "lease_ttl_s": 10.0},
        config_file=args.config,           # TOML / YAML / JSON, optional
        env_prefix="DYN_")

Env mapping: ``DYN_LEASE_TTL_S=30`` overrides key ``lease_ttl_s``;
nested keys join with a double underscore, ``DYN_CONTROL_PLANE__PORT=9000``
overrides ``control_plane.port``. Values parse as JSON when possible
(numbers, bools, lists), else stay strings — figment's env-parsing
behavior. The config file path itself can come from ``DYN_CONFIG``.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

__all__ = ["load_settings", "Settings"]


def _parse_scalar(text: str) -> Any:
    try:
        return json.loads(text)
    except (json.JSONDecodeError, ValueError):
        return text


def _load_toml(body: str) -> Dict[str, Any]:
    try:
        import tomllib  # 3.11+
    except ImportError:  # pragma: no cover — 3.10 fallback
        try:
            import tomli as tomllib
        except ImportError as e:
            raise RuntimeError(
                "TOML config requires Python >= 3.11 (tomllib) or the "
                "tomli package; use YAML or JSON instead") from e
    return tomllib.loads(body)


def _read_config_file(path: str) -> Dict[str, Any]:
    with open(path) as f:
        body = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml
        return yaml.safe_load(body) or {}
    if path.endswith(".toml"):
        return _load_toml(body)
    if path.endswith(".json"):
        return json.loads(body)
    # extension-less: try JSON, then YAML, then TOML
    try:
        return json.loads(body)
    except json.JSONDecodeError:
        pass
    try:
        import yaml
        out = yaml.safe_load(body)
        if isinstance(out, dict):
            return out
    except Exception:  # noqa: BLE001 — fall through to TOML
        pass
    return _load_toml(body)


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _env_overrides(defaults: Dict[str, Any], env_prefix: str,
                   environ: Dict[str, str]) -> Dict[str, Any]:
    """DYN_A__B=c -> {"a": {"b": parsed(c)}} for keys present in defaults.

    Only keys that exist in the defaults tree are taken: unrelated DYN_*
    process envs (DYN_COORD_ADDR etc. consumed elsewhere) must not leak
    into the settings object as junk keys.
    """
    out: Dict[str, Any] = {}
    # shallow keys first: DYN_A=... then DYN_A__B=... must nest cleanly
    # (the deeper override wins over a parent-scalar assignment instead of
    # crashing on a str cursor or being silently replaced)
    names = sorted((n for n in environ if n.startswith(env_prefix)),
                   key=lambda n: n.count("__"))
    for name in names:
        value = environ[name]
        path = name[len(env_prefix):].lower().split("__")
        node, cursor = defaults, out
        ok = True
        for part in path[:-1]:
            if not isinstance(node, dict) or part not in node:
                ok = False
                break
            node = node[part]
            if not isinstance(cursor.get(part), dict):
                cursor[part] = {}
            cursor = cursor[part]
        if not ok or not isinstance(node, dict) or path[-1] not in node:
            continue
        cursor[path[-1]] = _parse_scalar(value)
    return out


class Settings(dict):
    """A dict with attribute access; nested dicts wrap lazily."""

    def __getattr__(self, name: str) -> Any:
        try:
            value = self[name]
        except KeyError:
            raise AttributeError(name) from None
        return Settings(value) if isinstance(value, dict) else value


def load_settings(defaults: Dict[str, Any],
                  config_file: Optional[str] = None,
                  env_prefix: str = "DYN_",
                  environ: Optional[Dict[str, str]] = None) -> Settings:
    """Layer defaults <- config file <- env; returns attribute-accessible
    Settings. `config_file=None` falls back to the DYN_CONFIG env var."""
    environ = dict(os.environ if environ is None else environ)
    layered = dict(defaults)
    path = config_file or environ.get(env_prefix + "CONFIG")
    if path:
        layered = _deep_merge(layered, _read_config_file(path))
    layered = _deep_merge(layered,
                          _env_overrides(defaults, env_prefix, environ))
    return Settings(layered)
