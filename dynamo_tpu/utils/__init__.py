"""Cross-cutting utilities: logging configuration, layered settings."""
