"""Durable prefill work queue.

Role of the reference's NATS JetStream prefill queue (reference:
examples/llm/utils/prefill_queue.py:25-56, nats_queue.py): decode workers
enqueue RemotePrefillRequests, any prefill worker dequeues — the queue load-
balances prefill work and survives worker churn (elastic xPyD, reference:
docs/disagg_serving.md:95-101). Rides the runtime Messaging queue primitives
(memory plane in-process, control-plane server across processes).

Consumption is LEASED (JetStream ack-wait semantics): `dequeue_leased`
hands out an item under a redelivery lease and `ack` settles it. A prefill
worker that dies between dequeue and ack no longer loses the item — the
lease expires and the item becomes visible to surviving consumers
(runtime/transports Messaging.queue_pop_leased). Plain `dequeue` remains
for callers that accept at-most-once.
"""
from __future__ import annotations

from typing import Optional, Tuple

import msgpack

from dynamo_tpu.disagg.protocols import RemotePrefillRequest
from dynamo_tpu.runtime import faults


def queue_name(namespace: str, model: str) -> str:
    return f"{namespace}.prefill_queue.{model or 'default'}"


class PrefillQueue:
    def __init__(self, messaging, namespace: str, model: str = ""):
        self.messaging = messaging
        self.name = queue_name(namespace, model)

    async def enqueue(self, req: RemotePrefillRequest) -> None:
        # msgpack, not JSON: multimodal requests carry raw pixel bytes
        # (ImagePart.data), which msgpack frames natively
        await self.messaging.queue_push(
            self.name, msgpack.packb(req.model_dump(), use_bin_type=True))

    async def dequeue(self, timeout: Optional[float] = None
                      ) -> Optional[RemotePrefillRequest]:
        # `queue.dequeue` failpoint fires BEFORE the pop, so an injected
        # drop/delay can never lose a dequeued item — consumers retry
        # and the item is still queued
        if faults.REGISTRY.enabled:
            await faults.REGISTRY.fire("queue.dequeue")
        payload = await self.messaging.queue_pop(self.name, timeout=timeout)
        if payload is None:
            return None
        return RemotePrefillRequest.model_validate(
            msgpack.unpackb(payload, raw=False))

    async def dequeue_leased(
            self, timeout: Optional[float] = None, lease_s: float = 30.0
    ) -> Optional[Tuple[RemotePrefillRequest, str]]:
        """Dequeue under a redelivery lease; returns (request, lease_token).
        The item is re-enqueued if `ack(token)` doesn't arrive within
        lease_s — size the lease above the worst-case prefill+transfer."""
        if faults.REGISTRY.enabled:  # pre-pop: injected faults lose nothing
            await faults.REGISTRY.fire("queue.dequeue")
        got = await self.messaging.queue_pop_leased(
            self.name, timeout=timeout, lease_s=lease_s)
        if got is None:
            return None
        payload, token = got
        return RemotePrefillRequest.model_validate(
            msgpack.unpackb(payload, raw=False)), token

    async def ack(self, token: str) -> None:
        """Settle a leased item (done or terminally failed — either way it
        must not be redelivered)."""
        await self.messaging.queue_ack(self.name, token)

    async def touch(self, token: str, lease_s: float = 30.0) -> bool:
        """Re-arm a leased item's redelivery deadline (JetStream
        in-progress ack). A prefill worker entering the transfer leg —
        which may legitimately outlast the dequeue lease when the link
        flaps and the sender resumes — touches the lease instead of the
        fleet sizing lease_s for the worst-case resume ladder. Returns
        False when the lease already expired (the item was redelivered;
        the caller's copy is now the duplicate and the decode-side
        commit protocol absorbs it)."""
        touch = getattr(self.messaging, "queue_touch", None)
        if touch is None:
            return True
        return await touch(self.name, token, lease_s=lease_s)

    async def depth(self) -> int:
        return await self.messaging.queue_depth(self.name)
