"""Durable prefill work queue.

Role of the reference's NATS JetStream prefill queue (reference:
examples/llm/utils/prefill_queue.py:25-56, nats_queue.py): decode workers
enqueue RemotePrefillRequests, any prefill worker dequeues — the queue load-
balances prefill work and survives worker churn (elastic xPyD, reference:
docs/disagg_serving.md:95-101). Rides the runtime Messaging queue primitives
(memory plane in-process, control-plane server across processes).

Consumption is LEASED (JetStream ack-wait semantics): `dequeue_leased`
hands out an item under a redelivery lease and `ack` settles it. A prefill
worker that dies between dequeue and ack no longer loses the item — the
lease expires and the item becomes visible to surviving consumers
(runtime/transports Messaging.queue_pop_leased). Plain `dequeue` remains
for callers that accept at-most-once.

**Multi-tenant QoS** (runtime/qos.py, ROADMAP item 5): constructed with a
`QosPolicy`, the queue becomes CLASS-AWARE — `enqueue` routes each item
into a per-class sub-queue (`{name}.q.{class}`) by its
`RemotePrefillRequest.qos`, and `dequeue_leased` serves the backlogged
classes by weighted deficit (StridePicker: stride scheduling, service
ratios converge to class weights) with the policy's BOUNDED-AGING
no-starvation guarantee — a backlogged batch class skipped `aging_limit`
consecutive dequeues is served next regardless (promotions counted on
QOS_STATS.queue_aging_promotions, the storm's starvation evidence).
Lease / ack / touch / poison semantics are UNCHANGED: each sub-queue is
an ordinary leased messaging queue, acks resolve to the sub-queue the
token was leased from, and the legacy base queue keeps working as the
default class (mixed fleets where some enqueuers predate the policy).
Without a policy the queue is byte-for-byte the old FIFO.
"""
from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Tuple

import msgpack

from dynamo_tpu.disagg.protocols import RemotePrefillRequest
from dynamo_tpu.runtime import faults
from dynamo_tpu.runtime.qos import QOS_STATS, QosPolicy, StridePicker


def queue_name(namespace: str, model: str) -> str:
    return f"{namespace}.prefill_queue.{model or 'default'}"


# bounded poll slice while every class sub-queue is empty (class-aware
# mode only; the legacy path blocks on the single queue as before)
_POLL_SLICE_S = 0.05
# per-sub-queue pop grab: long enough to win the race with a concurrent
# push the depth probe just saw, short enough not to stall the scan
_GRAB_S = 0.02


class PrefillQueue:
    def __init__(self, messaging, namespace: str, model: str = "",
                 qos_policy: Optional[QosPolicy] = None):
        self.messaging = messaging
        self.name = queue_name(namespace, model)
        self.qos_policy = qos_policy
        self._picker = StridePicker(qos_policy) if qos_policy else None
        # lease token -> sub-queue it was popped from (class-aware acks;
        # tokens from other processes fall back to the base name, which
        # every transport resolves by token anyway)
        self._lease_queues: Dict[str, str] = {}

    def _class_queue(self, cls: str) -> str:
        return f"{self.name}.q.{cls}"

    async def enqueue(self, req: RemotePrefillRequest) -> None:
        # msgpack, not JSON: multimodal requests carry raw pixel bytes
        # (ImagePart.data), which msgpack frames natively
        payload = msgpack.packb(req.model_dump(), use_bin_type=True)
        name = self.name
        if self.qos_policy is not None:
            name = self._class_queue(
                self.qos_policy.resolve(req.qos or None).name)
        await self.messaging.queue_push(name, payload)

    async def dequeue(self, timeout: Optional[float] = None
                      ) -> Optional[RemotePrefillRequest]:
        # `queue.dequeue` failpoint fires BEFORE the pop, so an injected
        # drop/delay can never lose a dequeued item — consumers retry
        # and the item is still queued
        if faults.REGISTRY.enabled:
            await faults.REGISTRY.fire("queue.dequeue")
        payload = await self.messaging.queue_pop(self.name, timeout=timeout)
        if payload is None:
            return None
        return RemotePrefillRequest.model_validate(
            msgpack.unpackb(payload, raw=False))

    async def dequeue_leased(
            self, timeout: Optional[float] = None, lease_s: float = 30.0
    ) -> Optional[Tuple[RemotePrefillRequest, str]]:
        """Dequeue under a redelivery lease; returns (request, lease_token).
        The item is re-enqueued if `ack(token)` doesn't arrive within
        lease_s — size the lease above the worst-case prefill+transfer.

        Class-aware mode serves backlogged classes by weighted deficit
        with the policy's bounded-aging no-starvation guarantee (a class
        skipped `aging_limit` consecutive dequeues is served next — see
        StridePicker; dynalint R19)."""
        if faults.REGISTRY.enabled:  # pre-pop: injected faults lose nothing
            await faults.REGISTRY.fire("queue.dequeue")
        if self.qos_policy is None:
            got = await self.messaging.queue_pop_leased(
                self.name, timeout=timeout, lease_s=lease_s)
            if got is None:
                return None
            payload, token = got
            return RemotePrefillRequest.model_validate(
                msgpack.unpackb(payload, raw=False)), token
        return await self._dequeue_leased_classed(timeout, lease_s)

    async def _dequeue_leased_classed(
            self, timeout: Optional[float], lease_s: float
    ) -> Optional[Tuple[RemotePrefillRequest, str]]:
        policy = self.qos_policy
        default = policy.resolve(None).name
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            # depth probe per class; the legacy base queue counts as
            # default-class backlog (mixed fleets)
            depths: Dict[str, int] = {}
            for cls in policy.names():
                d = await self.messaging.queue_depth(
                    self._class_queue(cls))
                if d:
                    depths[cls] = d
            base_depth = await self.messaging.queue_depth(self.name)
            if base_depth:
                depths[default] = depths.get(default, 0) + base_depth
            order = self._picker.order(list(depths))
            for cls in order:
                names = [self._class_queue(cls)]
                if cls == default and base_depth:
                    names.append(self.name)
                for name in names:
                    got = await self.messaging.queue_pop_leased(
                        name, timeout=_GRAB_S, lease_s=lease_s)
                    if got is None:
                        continue
                    before = self._picker.aging_promotions
                    self._picker.charge(cls, list(depths))
                    QOS_STATS.queue_aging_promotions += \
                        self._picker.aging_promotions - before
                    payload, token = got
                    self._lease_queues[token] = name
                    return RemotePrefillRequest.model_validate(
                        msgpack.unpackb(payload, raw=False)), token
            # every sub-queue empty (or raced away): bounded poll slice
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    return None
                await asyncio.sleep(min(_POLL_SLICE_S, left))
            else:
                await asyncio.sleep(_POLL_SLICE_S)

    async def ack(self, token: str) -> None:
        """Settle a leased item (done or terminally failed — either way it
        must not be redelivered). Resolves to the sub-queue the token
        was leased from (class-aware mode)."""
        await self.messaging.queue_ack(
            self._lease_queues.pop(token, self.name), token)

    async def touch(self, token: str, lease_s: float = 30.0) -> bool:
        """Re-arm a leased item's redelivery deadline (JetStream
        in-progress ack). A prefill worker entering the transfer leg —
        which may legitimately outlast the dequeue lease when the link
        flaps and the sender resumes — touches the lease instead of the
        fleet sizing lease_s for the worst-case resume ladder. Returns
        False when the lease already expired (the item was redelivered;
        the caller's copy is now the duplicate and the decode-side
        commit protocol absorbs it)."""
        touch = getattr(self.messaging, "queue_touch", None)
        if touch is None:
            return True
        return await touch(self._lease_queues.get(token, self.name),
                           token, lease_s=lease_s)

    async def depth(self) -> int:
        total = await self.messaging.queue_depth(self.name)
        if self.qos_policy is not None:
            for cls in self.qos_policy.classes:
                total += await self.messaging.queue_depth(
                    self._class_queue(cls))
        return total
